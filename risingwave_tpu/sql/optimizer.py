"""Logical plan IR + heuristic rewrite rules (the optimizer).

Reference: src/frontend/src/optimizer/ — plan-node forest with staged
heuristic optimization (`optimize_by_rules`, logical_optimization.rs:38,
111) over 66 rules; predicate pushdown, projection pruning, outer-join
simplification are the load-bearing classics this module implements.

Shape here: parser AST -> logical IR (build) -> rule passes to a fixed
point -> optimized AST (emit) -> the pattern planner lowers to executor
pipelines as before. The IR is the optimization surface; lowering
reuses the proven AST path (the reference lowers Logical* -> Stream*
plan nodes instead — our executors play the Stream* role).

Rules:
- SplitFilter / MergeFilter: conjunct normalization
- PushFilterThroughProject: rewrite via the projection's alias map
- PushFilterThroughJoin: route conjuncts to the side that owns their
  columns (cross-side conjuncts stay at the join)
- PushFilterThroughAgg: predicates on group keys move below the agg
- SimplifyOuterJoin: a null-rejecting predicate on the nullable side
  turns LEFT/RIGHT/FULL into INNER (the reference's
  translate_apply / outer-join-to-inner rules)
- FoldTrivialPred: drop always-true conjuncts, fold literal arithmetic
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Sequence, Set, Tuple

from risingwave_tpu.sql import parser as P

# ---------------------------------------------------------------------------
# Logical IR
# ---------------------------------------------------------------------------


@dataclass
class LScan:
    table: str
    alias: Optional[str] = None
    cols: Optional[frozenset] = None  # known schema (catalog-resolved)


@dataclass
class LWindow:
    input: object
    ts_col: str
    size_ms: int
    slide_ms: int
    alias: Optional[str] = None


@dataclass
class LFilter:
    input: object
    conjuncts: List[object]  # AST predicates, AND-ed


@dataclass
class LAggProject:
    """The select head: items (+ optional GROUP BY). Carries the
    subquery alias when this level came from a derived table."""

    input: object
    items: Tuple[P.SelectItem, ...]
    group_by: Tuple[P.Ident, ...]
    alias: Optional[str] = None
    order_by: Tuple = ()
    limit: Optional[int] = None
    grouping_sets: Tuple = ()
    having: Optional[object] = None
    distinct: bool = False


@dataclass
class LJoin:
    left: object
    right: object
    on: object
    join_type: str


# ---------------------------------------------------------------------------
# build: AST -> IR
# ---------------------------------------------------------------------------


def build(
    select: P.Select, alias: Optional[str] = None, catalog=None
) -> LAggProject:
    node = _build_rel(select.from_, catalog)
    if select.where is not None:
        node = LFilter(node, _split_conjuncts(select.where))
    return LAggProject(
        node,
        select.items,
        select.group_by,
        alias=alias,
        order_by=select.order_by,
        limit=select.limit,
        grouping_sets=select.grouping_sets,
        having=select.having,
        distinct=select.distinct,
    )


def _build_rel(rel, catalog=None):
    if isinstance(rel, P.TableRef):
        cols = None
        if catalog is not None and rel.name in getattr(catalog, "tables", {}):
            cols = frozenset(catalog.tables[rel.name].names)
        return LScan(rel.name, rel.alias, cols)
    if isinstance(rel, P.WindowTVF):
        return LWindow(
            _build_rel(rel.table, catalog), rel.ts_col, rel.size_ms,
            rel.slide_ms, rel.alias,
        )
    if isinstance(rel, P.SubQuery):
        return build(rel.select, alias=rel.alias, catalog=catalog)
    if isinstance(rel, P.Join):
        return LJoin(
            _build_rel(rel.left, catalog),
            _build_rel(rel.right, catalog),
            rel.on,
            rel.join_type,
        )
    raise TypeError(f"cannot build IR for {rel!r}")


def _split_conjuncts(pred) -> List[object]:
    if isinstance(pred, P.BinaryOp) and pred.op == "and":
        return _split_conjuncts(pred.left) + _split_conjuncts(pred.right)
    return [pred]


def _and_all(conjuncts: Sequence[object]):
    out = None
    for c in conjuncts:
        out = c if out is None else P.BinaryOp("and", out, c)
    return out


# ---------------------------------------------------------------------------
# column ownership / visibility
# ---------------------------------------------------------------------------


def _visible(node) -> Tuple[Set[str], Set[str]]:
    """(column names, qualifiers) a node's output exposes. Column set
    may be OPEN (unknown scan schema): signalled by returning None."""
    if isinstance(node, LScan):
        quals = {node.alias or node.table}
        return (set(node.cols) if node.cols is not None else None), quals
    if isinstance(node, LWindow):
        cols, quals = _visible(node.input)
        if node.alias:
            quals = {node.alias}
        if cols is not None:
            cols = cols | {"window_start", "window_end"}
        return cols, quals
    if isinstance(node, LFilter):
        return _visible(node.input)
    if isinstance(node, LAggProject):
        cols = set()
        for i, item in enumerate(node.items):
            if item.alias:
                cols.add(item.alias)
            elif isinstance(item.expr, P.Ident):
                cols.add(item.expr.name)
        quals = {node.alias} if node.alias else set()
        return cols, quals
    if isinstance(node, LJoin):
        lc, lq = _visible(node.left)
        rc, rq = _visible(node.right)
        cols = None if lc is None or rc is None else lc | rc
        return cols, lq | rq
    raise TypeError(node)


def _pred_sites(pred) -> List[P.Ident]:
    from risingwave_tpu.sql.planner import _idents_in

    return list(_idents_in(pred))


def _owned_by(pred, node) -> bool:
    """True iff every column reference in pred resolves inside node."""
    cols, quals = _visible(node)
    for ident in _pred_sites(pred):
        if ident.qualifier is not None:
            if ident.qualifier not in quals:
                return False
            continue
        if cols is None:
            return False  # open schema, unqualified: cannot prove
        if ident.name not in cols:
            return False
    return True


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _fold_pred(pred):
    """Literal-only arithmetic/comparison folding."""
    if isinstance(pred, P.BinaryOp):
        left = _fold_pred(pred.left)
        right = _fold_pred(pred.right)
        if (
            isinstance(left, P.Literal)
            and isinstance(right, P.Literal)
            and left.value is not None
            and right.value is not None  # NULL comparisons are NULL in
            # SQL (filter-out), not Python's True/False
        ):
            a, b = left.value, right.value
            try:
                val = {
                    "+": lambda: a + b,
                    "-": lambda: a - b,
                    "*": lambda: a * b,
                    "=": lambda: a == b,
                    "<>": lambda: a != b,
                    "<": lambda: a < b,
                    "<=": lambda: a <= b,
                    ">": lambda: a > b,
                    ">=": lambda: a >= b,
                    "and": lambda: bool(a) and bool(b),
                    "or": lambda: bool(a) or bool(b),
                }[pred.op]()
                return P.Literal(val)
            except (KeyError, TypeError):
                pass
        return P.BinaryOp(pred.op, left, right)
    return pred


_STRICT_ARITH = ("+", "-", "*", "/", "%")


def _null_strict(expr) -> bool:
    """True only when a NULL in ANY input ident forces the expression
    itself to NULL. CASE/COALESCE-like constructs can map NULL inputs
    to non-NULL outputs, so any appearance makes the tree non-strict."""
    if isinstance(expr, (P.Ident, P.Literal)):
        return True
    if isinstance(expr, P.BinaryOp) and expr.op in _STRICT_ARITH:
        return _null_strict(expr.left) and _null_strict(expr.right)
    if isinstance(expr, P.UnaryOp) and expr.op == "-":
        return _null_strict(expr.operand)
    return False


def _null_rejecting_side(pred, join: LJoin) -> Optional[str]:
    """Which side of the join this predicate null-rejects ("left" /
    "right" / None). Conservative: comparisons and IS NOT NULL reject
    NULL inputs only when their operands are NULL-strict — a CASE over
    the padded side can turn a NULL row into a satisfying value, so it
    must NOT trigger outer-join reduction."""
    if isinstance(pred, P.BinaryOp) and pred.op in (
        "=", "<>", "<", "<=", ">", ">=",
    ):
        rejecting = _null_strict(pred.left) and _null_strict(pred.right)
    elif isinstance(pred, P.UnaryOp) and pred.op == "is not null":
        rejecting = _null_strict(pred.operand)
    else:
        rejecting = False
    if not rejecting:
        return None
    if _owned_by(pred, join.left):
        return "left"
    if _owned_by(pred, join.right):
        return "right"
    return None


def _strip_filter(node):
    return node.input if isinstance(node, LFilter) else node


def _can_push(core: "LAggProject", c) -> bool:
    """May this conjunct move BELOW this projection? Shared by direct
    pushdown and join-arm absorption (one rule, no divergence):
    - never below ORDER BY/LIMIT (a TopN selects rows FIRST; filtering
      before it picks different rows);
    - every referenced output column must substitute to an agg-free
      expr, and below a GROUP BY only group keys qualify."""
    if core.limit is not None or core.order_by:
        return False
    amap, group_names = _alias_map(core)
    # a select containing ANY window call ranks over its full input
    # row set: a predicate may sink below it only if it references
    # nothing but columns present in EVERY window's PARTITION BY
    # (filtering whole partitions cannot change in-partition values)
    win_parts = None
    for target in amap.values():
        if isinstance(target, P.WindowFuncCall):
            names = {i.name for i in target.partition_by}
            win_parts = (
                names if win_parts is None else win_parts & names
            )
    for ident in _pred_sites(c):
        target = amap.get(ident.name)
        if target is None or _contains_agg(target) or _contains_window(
            target
        ):
            # a window-computed output (e.g. row_number()) is defined
            # only ABOVE the over-window stage: filtering before it
            # would rank a different row set
            return False
        if win_parts is not None and not (
            isinstance(target, P.Ident) and target.name in win_parts
        ):
            return False
        if core.group_by and not (
            isinstance(target, P.Ident) and target.name in group_names
        ):
            return False
    return True


def _contains_window(ast) -> bool:
    if isinstance(ast, P.WindowFuncCall):
        return True
    if isinstance(ast, P.FuncCall):
        return any(
            _contains_window(a)
            for a in ast.args
            if not isinstance(a, str)
        )
    if isinstance(ast, P.BinaryOp):
        return _contains_window(ast.left) or _contains_window(ast.right)
    if isinstance(ast, P.UnaryOp):
        return _contains_window(ast.operand)
    if isinstance(ast, P.CaseExpr):
        return any(
            _contains_window(x)
            for b in ast.branches
            for x in b
        ) or (
            ast.default is not None and _contains_window(ast.default)
        )
    return False


def _absorbable(arm, c) -> bool:
    """Can this conjunct sink INTO a join arm? Only derived tables
    (LAggProject) can absorb — bare scans/windows have no emit form for
    an attached filter."""
    core = _strip_filter(arm)
    if not isinstance(core, LAggProject):
        return False
    if not _owned_by(c, arm):
        return False
    return _can_push(core, c)


def _alias_map(node: LAggProject):
    """output name -> defining expr, plus the set of group-key names."""
    amap: Dict[str, object] = {}
    for item in node.items:
        name = item.alias or (
            item.expr.name if isinstance(item.expr, P.Ident) else None
        )
        if name is not None:
            amap[name] = item.expr
    group_names = {g.name for g in node.group_by}
    return amap, group_names


def _push_into(node, conjuncts: List[object]):
    """Push conjuncts as deep as they can go; returns the new node.
    Conjuncts that cannot move below stay in a filter at this level."""
    if not conjuncts:
        return node

    if isinstance(node, LFilter):
        return _push_into(node.input, node.conjuncts + conjuncts)

    if isinstance(node, LJoin):
        left_c, right_c, here = [], [], []
        for c in conjuncts:
            # pushing a filter below an outer join's null-padded side
            # would change results; only the row-preserved side accepts
            can_left = node.join_type in (
                "inner", "left", "left_semi", "left_anti",
            )
            can_right = node.join_type in ("inner", "right")
            if can_left and _absorbable(node.left, c):
                left_c.append(c)
            elif can_right and _absorbable(node.right, c):
                right_c.append(c)
            else:
                here.append(c)
        new = LJoin(
            _push_into(node.left, left_c) if left_c else node.left,
            _push_into(node.right, right_c) if right_c else node.right,
            node.on,
            node.join_type,
        )
        return LFilter(new, here) if here else new

    if isinstance(node, LAggProject):
        below, here = [], []
        amap, _ = _alias_map(node)
        for c in conjuncts:
            if _can_push(node, c):
                below.append(_substitute(c, amap))
            else:
                here.append(c)
        new = replace(node, input=_push_into(node.input, below))
        return LFilter(new, here) if here else new

    # bare scan / window: the filter stays directly above — emitted as
    # this level's WHERE (never inside a join arm, see _absorbable)
    return LFilter(node, conjuncts)


def _contains_agg(ast) -> bool:
    from risingwave_tpu.sql.planner import AGG_FUNCS, EXTENDED_AGGS

    if isinstance(ast, P.FuncCall):
        if ast.name in AGG_FUNCS or ast.name in EXTENDED_AGGS:
            return True
        return any(
            _contains_agg(a) for a in ast.args if not isinstance(a, str)
        )
    if isinstance(ast, P.BinaryOp):
        return _contains_agg(ast.left) or _contains_agg(ast.right)
    if isinstance(ast, P.UnaryOp):
        return _contains_agg(ast.operand)
    return False


def _substitute(pred, amap: Dict[str, object]):
    """Replace output-name references with their defining exprs (strip
    the derived-table qualifier as it crosses the boundary)."""
    if isinstance(pred, P.Ident):
        return amap.get(pred.name, P.Ident(pred.name))
    if isinstance(pred, P.BinaryOp):
        return P.BinaryOp(
            pred.op, _substitute(pred.left, amap), _substitute(pred.right, amap)
        )
    if isinstance(pred, P.UnaryOp):
        return P.UnaryOp(pred.op, _substitute(pred.operand, amap))
    if isinstance(pred, P.FuncCall):
        return P.FuncCall(
            pred.name,
            tuple(
                a if isinstance(a, str) else _substitute(a, amap)
                for a in pred.args
            ),
            distinct=pred.distinct,
        )
    if isinstance(pred, P.CaseExpr):
        return P.CaseExpr(
            tuple(
                (_substitute(c, amap), _substitute(v, amap))
                for c, v in pred.branches
            ),
            _substitute(pred.default, amap)
            if pred.default is not None
            else None,
        )
    return pred


def optimize(node):
    """Apply all rules to a fixed point (staged heuristics,
    logical_optimization.rs:38)."""
    node = _simplify_outer(node)
    node = _pushdown(node)
    node = _prune_filters(node)
    return node


def _pushdown(node):
    if isinstance(node, LFilter):
        return _push_into(_pushdown(node.input), node.conjuncts)
    if isinstance(node, LAggProject):
        return replace(node, input=_pushdown(node.input))
    if isinstance(node, LWindow):
        return replace(node, input=_pushdown(node.input))
    if isinstance(node, LJoin):
        return LJoin(
            _pushdown(node.left), _pushdown(node.right), node.on, node.join_type
        )
    return node


def _simplify_outer(node):
    """WHERE null-rejecting on an outer join's padded side -> inner."""
    if isinstance(node, LFilter):
        inner = _simplify_outer(node.input)
        if isinstance(inner, LJoin) and inner.join_type in (
            "left", "right", "full",
        ):
            jt = inner.join_type
            for c in node.conjuncts:
                side = _null_rejecting_side(c, inner)
                if side == "right" and jt in ("left", "full"):
                    jt = "inner" if jt == "left" else "right"
                elif side == "left" and jt in ("right", "full"):
                    jt = "inner" if jt == "right" else "left"
            if jt != inner.join_type:
                inner = LJoin(inner.left, inner.right, inner.on, jt)
        return LFilter(inner, node.conjuncts)
    if isinstance(node, LAggProject):
        return replace(node, input=_simplify_outer(node.input))
    if isinstance(node, LWindow):
        return replace(node, input=_simplify_outer(node.input))
    if isinstance(node, LJoin):
        return LJoin(
            _simplify_outer(node.left),
            _simplify_outer(node.right),
            node.on,
            node.join_type,
        )
    return node


def _prune_filters(node):
    """Fold literal predicates; drop always-true conjuncts."""
    if isinstance(node, LFilter):
        inner = _prune_filters(node.input)
        kept = []
        for c in node.conjuncts:
            f = _fold_pred(c)
            if isinstance(f, P.Literal) and f.value is True:
                continue
            kept.append(f)
        return LFilter(inner, kept) if kept else inner
    if isinstance(node, LAggProject):
        return replace(node, input=_prune_filters(node.input))
    if isinstance(node, LWindow):
        return replace(node, input=_prune_filters(node.input))
    if isinstance(node, LJoin):
        return LJoin(
            _prune_filters(node.left),
            _prune_filters(node.right),
            node.on,
            node.join_type,
        )
    return node


# ---------------------------------------------------------------------------
# emit: IR -> AST
# ---------------------------------------------------------------------------


def emit(node: LAggProject) -> P.Select:
    if not isinstance(node, LAggProject):
        raise TypeError("top of an optimized plan must be a projection")
    where = None
    inner = node.input
    if isinstance(inner, LFilter):
        where = _and_all(inner.conjuncts)
        inner = inner.input
    return P.Select(
        items=node.items,
        from_=_emit_rel(inner),
        where=where,
        group_by=node.group_by,
        order_by=node.order_by,
        limit=node.limit,
        grouping_sets=node.grouping_sets,
        having=node.having,
        distinct=node.distinct,
    )


def _emit_rel(node):
    if isinstance(node, LScan):
        return P.TableRef(node.table, node.alias)
    if isinstance(node, LWindow):
        inner = _emit_rel(node.input)
        if not isinstance(inner, P.TableRef):
            raise TypeError("window TVF over non-table after optimization")
        return P.WindowTVF(
            "hop" if node.slide_ms != node.size_ms else "tumble",
            inner,
            node.ts_col,
            node.size_ms,
            node.slide_ms,
            node.alias,
        )
    if isinstance(node, LFilter):
        raise TypeError(
            "filter over a bare relation inside a join arm — _absorbable "
            "should have kept it at the join level"
        )
    if isinstance(node, LAggProject):
        return P.SubQuery(emit(node), alias=node.alias or "__sq")
    if isinstance(node, LJoin):
        return P.Join(
            _emit_rel(node.left), _emit_rel(node.right), node.on, node.join_type
        )
    raise TypeError(node)


def optimize_select(select: P.Select, catalog=None) -> P.Select:
    """AST -> IR -> rules -> AST. The public entry the planner uses.
    HAVING/DISTINCT ride AROUND the IR (no rule touches them: HAVING
    filters agg OUTPUT, which pushdown must never move below the agg)."""
    import dataclasses

    out = emit(optimize(build(select, catalog=catalog)))
    if select.having is not None or select.distinct:
        out = dataclasses.replace(
            out, having=select.having, distinct=select.distinct
        )
    return out


# ---------------------------------------------------------------------------
# EXPLAIN
# ---------------------------------------------------------------------------


def explain(node, indent: int = 0) -> str:
    """Reference-style plan dump (planner-test yaml look)."""
    pad = "  " * indent
    if isinstance(node, LAggProject):
        keys = ", ".join(g.name for g in node.group_by)
        head = "LogicalAgg" if node.group_by else "LogicalProject"
        extra = f" group_by=[{keys}]" if keys else ""
        items = ", ".join(
            (i.alias or _expr_str(i.expr)) for i in node.items
        )
        return (
            f"{pad}{head}{extra} items=[{items}]\n"
            + explain(node.input, indent + 1)
        )
    if isinstance(node, LFilter):
        preds = " AND ".join(_expr_str(c) for c in node.conjuncts)
        return f"{pad}LogicalFilter [{preds}]\n" + explain(
            node.input, indent + 1
        )
    if isinstance(node, LJoin):
        return (
            f"{pad}LogicalJoin type={node.join_type} on={_expr_str(node.on)}\n"
            + explain(node.left, indent + 1)
            + explain(node.right, indent + 1)
        )
    if isinstance(node, LWindow):
        kind = "hop" if node.slide_ms != node.size_ms else "tumble"
        return (
            f"{pad}LogicalHopWindow kind={kind} ts={node.ts_col} "
            f"size={node.size_ms}ms slide={node.slide_ms}ms\n"
            + explain(node.input, indent + 1)
        )
    if isinstance(node, LScan):
        a = f" as {node.alias}" if node.alias else ""
        return f"{pad}LogicalScan {node.table}{a}\n"
    return f"{pad}{node!r}\n"


def _expr_str(ast) -> str:
    if isinstance(ast, P.Ident):
        return f"{ast.qualifier}.{ast.name}" if ast.qualifier else ast.name
    if isinstance(ast, P.Literal):
        return repr(ast.value)
    if isinstance(ast, P.BinaryOp):
        return f"({_expr_str(ast.left)} {ast.op} {_expr_str(ast.right)})"
    if isinstance(ast, P.UnaryOp):
        return f"({ast.op} {_expr_str(ast.operand)})"
    if isinstance(ast, P.FuncCall):
        args = ", ".join(
            a if isinstance(a, str) else _expr_str(a) for a in ast.args
        )
        return f"{ast.name}({args})"
    return repr(ast)


def explain_sql(sql: str, catalog=None) -> str:
    """EXPLAIN: original + optimized logical plans."""
    stmt = P.parse(sql)
    if isinstance(stmt, P.CreateMaterializedView):
        select = stmt.select
    elif isinstance(stmt, P.Select):
        select = stmt
    else:
        raise ValueError("EXPLAIN supports SELECT / CREATE MV")
    before = build(select, catalog=catalog)
    after = optimize(build(select, catalog=catalog))
    return (
        "-- logical plan\n"
        + explain(before)
        + "-- optimized\n"
        + explain(after)
    )
