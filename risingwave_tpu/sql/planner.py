"""Binder + streaming planner: SQL AST -> executor pipeline.

Reference roles:
- Binder (src/frontend/src/binder/): name resolution against a catalog;
- Planner + optimizer (src/frontend/src/planner/, optimizer/): bound
  query -> stream plan. This v0 is a PATTERN planner: it recognizes the
  streaming shapes our executors implement (the same specializations
  RW's rules produce on these queries) instead of a rewrite engine:
    * window TVF         -> HopWindowExecutor
    * WHERE              -> FilterExecutor
    * computed items     -> ProjectExecutor
    * GROUP BY + aggs    -> HashAggExecutor
    * GROUP BY, no aggs  -> AppendOnlyDedupExecutor (append-only DISTINCT)
    * JOIN ... ON eq     -> HashJoinExecutor (TwoInputPipeline)
    * no pk available    -> RowIdGenExecutor (hidden _row_id, row_id_gen.rs)
- Stream fragmenter (src/frontend/src/stream_fragmenter/): here one
  fragment per input stream — the TwoInputPipeline split.

The planner returns a PlannedMV: pipeline + materialize + the input
stream name(s) the driver feeds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp

from risingwave_tpu.executors import (
    AppendOnlyDedupExecutor,
    Executor,
    FilterExecutor,
    HashAggExecutor,
    HashJoinExecutor,
    HopWindowExecutor,
    MaterializeExecutor,
    ProjectExecutor,
)
from risingwave_tpu.executors.materialize import DeviceMaterializeExecutor
from risingwave_tpu.executors.row_id_gen import RowIdGenExecutor
from risingwave_tpu.expr import expr as E
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime import Pipeline, TwoInputPipeline
from risingwave_tpu.sql import parser as P
from risingwave_tpu.types import Schema

AGG_FUNCS = {"count": "count", "sum": "sum", "min": "min", "max": "max"}

# Composite aggregates lowered onto the base kinds + a finishing
# projection (the reference ships these as first-class agg kernels,
# src/expr/impl/src/aggregate/general.rs + stddev via sum/count
# decomposition in the frontend; here the decomposition IS the plan:
# hidden sum/count/sum-of-squares calls feed one post-agg Project, so
# retraction, checkpointing, sharding, and two-phase splits all come
# for free from the base machinery).
EXTENDED_AGGS = (
    "avg",
    "var_pop",
    "var_samp",
    "stddev_pop",
    "stddev_samp",
    "bool_and",
    "bool_or",
)

# DISTINCT aggregates lower onto an AppendOnlyDedupExecutor keyed
# (group keys, distinct column) feeding a plain count — the reference
# keeps per-agg distinct dedup tables (executor/aggregation/
# distinct.rs); here the dedup IS an executor stage, so checkpointing
# and sharding reuse its machinery. approx_count_distinct shares the
# lowering (an exact answer is a valid approximation; the reference's
# HLL trades exactness for bounded state).
DISTINCT_AGGS = ("approx_count_distinct",)


def _is_distinct_agg(ast) -> bool:
    return isinstance(ast, P.FuncCall) and (
        ast.name in DISTINCT_AGGS
        or (ast.name in AGG_FUNCS and getattr(ast, "distinct", False))
    )


def _distinct_dedup_stage(select, binder, keys, schema, capacity, table_id):
    """Validate a select's DISTINCT aggregates and build their shared
    dedup prefix: [NULL filter on the distinct column (PG ignores NULL
    inputs), AppendOnlyDedupExecutor keyed (group keys, column)].
    Returns [] when the select has no DISTINCT aggregates.

    Known divergence: a group whose rows ALL have a NULL distinct
    column is dropped entirely (PG keeps it with count 0) — the NULL
    filter removes its rows before grouping."""
    items = select.items
    if not any(_is_distinct_agg(it.expr) for it in items):
        return [], None
    dcols = [
        binder.resolve(it.expr.args[0])
        for it in items
        if _is_distinct_agg(it.expr)
        and it.expr.args != ("*",)
        and isinstance(it.expr.args[0], P.Ident)
    ]
    n_distinct = sum(1 for it in items if _is_distinct_agg(it.expr))
    if len(dcols) != n_distinct:
        raise ValueError("DISTINCT aggregates take one bare column")
    if len(set(dcols)) != 1:
        raise NotImplementedError(
            "all DISTINCT aggregates in one select must share a column"
        )
    if any(
        _is_agg(it.expr) and not _is_distinct_agg(it.expr)
        for it in items
    ):
        raise NotImplementedError(
            "mixing DISTINCT and plain aggregates: split into two MVs"
        )
    dcol = dcols[0]
    stage = [
        FilterExecutor(E.IsNull(E.col(dcol), negate=True)),
        # the filter removed NULL rows but not the column's NULL LANE;
        # strip it so the dedup's null-free key contract holds
        ProjectExecutor(
            {
                c: (
                    E.AssumeNotNull(E.col(c)) if c == dcol else E.col(c)
                )
                for c in schema
            }
        ),
        AppendOnlyDedupExecutor(
            keys=tuple(keys) + (dcol,),
            schema_dtypes=schema,
            capacity=capacity,
            table_id=table_id,
        ),
    ]
    return stage, dcol


def _ext_agg_acc():
    """Shared-state accumulator for extended-agg lowering: hidden base
    calls are DEDUPED by (kind, input) so ``avg(v), stddev_samp(v)``
    carries one sum(v) + one count(v), not two of each."""
    return {"calls": [], "pre": {}, "hidden": {}}


def _lower_extended_agg(kind: str, incol: str, acc: dict):
    """Lower one extended aggregate over ``incol`` into a finishing
    Expr + output dtype, appending its (deduped) base AggCalls and
    pre-projected inputs (x*x for variance, int cast for bool_and/or)
    into ``acc``.

    NULL semantics follow PG: avg/var/stddev over zero non-null rows
    is NULL (0/0 division -> NULL via the non-strict ``/`` guard);
    var_samp/stddev_samp of a single row is NULL (n-1 = 0).
    """

    def base(k: str, col: str):
        key = (k, col)
        if key not in acc["hidden"]:
            out = f"__x{len(acc['hidden'])}"
            acc["hidden"][key] = out
            acc["calls"].append(AggCall(k, col, out))
        return E.col(acc["hidden"][key])

    if kind == "avg":
        fin = E.BinOp("/", base("sum", incol), base("count", incol))
        return fin, jnp.dtype(jnp.float64)
    if kind in ("bool_and", "bool_or"):
        bcol = f"__xb_{incol}"
        acc["pre"][bcol] = (
            E.Cast(E.col(incol), jnp.int64),
            jnp.dtype(jnp.int64),
        )
        m = base("min" if kind == "bool_and" else "max", bcol)
        return E.BinOp("!=", m, E.lit(0)), jnp.dtype(jnp.bool_)
    # variance family: E[x^2] - E[x]^2 (pop) / (q - s*mean)/(n-1) (samp)
    qcol = f"__xq_{incol}"
    fx = E.Cast(E.col(incol), jnp.float64)
    acc["pre"][qcol] = (E.BinOp("*", fx, fx), jnp.dtype(jnp.float64))
    n = base("count", incol)
    s = E.Cast(base("sum", incol), jnp.float64)
    q = base("sum", qcol)
    mean = E.BinOp("/", s, n)
    if kind in ("var_pop", "stddev_pop"):
        var = E.BinOp("-", E.BinOp("/", q, n), E.BinOp("*", mean, mean))
    else:
        var = E.BinOp(
            "/",
            E.BinOp("-", q, E.BinOp("*", mean, s)),
            E.BinOp("-", n, E.lit(1)),
        )
    from risingwave_tpu.expr import functions as _F

    var = _F.Func("greatest", (var, E.lit(0.0)))  # clamp fp cancellation
    fin = _F.Func("sqrt", (var,)) if kind.startswith("stddev") else var
    return fin, jnp.dtype(jnp.float64)


@dataclass
class BoundRel:
    """One planned input chain: executors + output schema + pk."""

    chain: List[Executor]
    schema: Dict[str, object]  # col name -> jnp dtype
    pk: Tuple[str, ...]
    source: str  # base stream name the driver pushes into
    alias: Optional[str]
    # set when the input is a window TVF over a watermark-declared
    # relation: downstream grouped aggs keyed on it clean closed
    # windows (window_key state cleaning)
    window_col: Optional[str] = None


def _join_inputs(lsrc: str, rsrc: str) -> Dict[str, str]:
    """Join input map; a SELF-join (both sides read one base stream,
    the Nexmark q7 shape) collapses to side "both" so the runtime
    feeds each source chunk to both inputs."""
    if lsrc == rsrc:
        return {lsrc: "both"}
    return {lsrc: "left", rsrc: "right"}


@dataclass
class PlannedMV:
    name: str
    pipeline: Union[Pipeline, TwoInputPipeline]
    mview: MaterializeExecutor
    inputs: Dict[str, str]  # base stream name -> "single"|"left"|"right"|"both"
    schema: Optional[Dict[str, object]] = None  # output col -> dtype
    # hidden MVs a multi-way join lowered into (registered by the
    # session BEFORE this one, in list order — deepest first; the
    # reference fragments an n-way join into a tree of 2-way
    # StreamHashJoins the same way)
    aux: Tuple["PlannedMV", ...] = ()


class Catalog:
    """Stream catalog: name -> Schema (reference: frontend catalog).

    Planned MVs register their output schema with ``add_mv`` so later
    statements can ``FROM <mv_name>`` (MV-on-MV; the runtime backfills
    the new MV from the upstream's snapshot, runtime/backfill.py)."""

    def __init__(self, tables: Dict[str, Schema]):
        self.tables = dict(tables)
        self.mvs: Dict[str, "PlannedMV"] = {}
        # CREATE INDEX registry: name -> {"base", "cols", "base_pk",
        # "arrangement"} (shared IndexArrangement instances; delta
        # joins plan against these, lookup.rs)
        self.indexes: Dict[str, dict] = {}
        self.enable_delta_join = False  # SET enable_delta_join = true
        # WATERMARK FOR declarations: relation -> (column, lag_ms)
        # (reference: watermark definitions on sources/tables)
        self.watermarks: Dict[str, Tuple[str, int]] = {}

    def schema_dtypes(self, name: str) -> Dict[str, object]:
        sch = self.tables[name]
        return {f.name: jnp.dtype(f.dtype.device_dtype) for f in sch.fields}

    def add_mv(self, planned: "PlannedMV") -> None:
        from risingwave_tpu.types import schema_from_dtypes

        if planned.schema is None:
            raise ValueError("planned MV carries no output schema")
        self.tables[planned.name] = schema_from_dtypes(planned.schema)
        self.mvs[planned.name] = planned

    def is_mv(self, name: str) -> bool:
        return name in self.mvs


class Binder:
    """Column resolution over a rel's output schema. ``alias`` may be a
    single name or a set of names (an enriched temporal-join schema is
    addressable through either side's qualifier)."""

    def __init__(self, schema: Dict[str, object], alias):
        self.schema = schema
        self.alias = alias

    def resolve(self, ident: P.Ident) -> str:
        if ident.qualifier is not None and self.alias is not None:
            ok = (
                ident.qualifier in self.alias
                if isinstance(self.alias, (set, frozenset))
                else ident.qualifier == self.alias
            )
            if not ok:
                raise KeyError(f"unknown qualifier {ident.qualifier!r}")
        if ident.name not in self.schema:
            raise KeyError(f"unknown column {ident.name!r}")
        return ident.name


def compile_scalar(ast, binder: Binder) -> E.Expr:
    """Scalar AST -> expr framework node (no aggregates allowed)."""
    if isinstance(ast, P.Literal):
        return E.lit(ast.value)
    if isinstance(ast, P.Ident):
        return E.col(binder.resolve(ast))
    if isinstance(ast, P.UnaryOp):
        if ast.op == "-":
            return E.lit(0) - compile_scalar(ast.operand, binder)
        if ast.op == "not":
            return E.Not(compile_scalar(ast.operand, binder))
        if ast.op == "is null":
            return E.IsNull(compile_scalar(ast.operand, binder))
        if ast.op == "is not null":
            return E.IsNull(compile_scalar(ast.operand, binder), negate=True)
    if isinstance(ast, P.BinaryOp):
        lhs = compile_scalar(ast.left, binder)
        rhs = compile_scalar(ast.right, binder)
        ops = {
            "+": lambda: lhs + rhs,
            "-": lambda: lhs - rhs,
            "*": lambda: lhs * rhs,
            "/": lambda: lhs // rhs,  # int division v0 (Nexmark is ints)
            "%": lambda: lhs % rhs,
            "=": lambda: lhs == rhs,
            "<>": lambda: lhs != rhs,
            "!=": lambda: lhs != rhs,
            "<": lambda: lhs < rhs,
            "<=": lambda: lhs <= rhs,
            ">": lambda: lhs > rhs,
            ">=": lambda: lhs >= rhs,
            "and": lambda: E.And(lhs, rhs),
            "or": lambda: E.Or(lhs, rhs),
        }
        return ops[ast.op]()
    if isinstance(ast, P.CaseExpr):
        branches = tuple(
            (compile_scalar(c, binder), compile_scalar(v, binder))
            for c, v in ast.branches
        )
        default = (
            compile_scalar(ast.default, binder)
            if ast.default is not None
            else E.lit(None)
        )
        return E.Case(branches, default)
    if isinstance(ast, P.FuncCall):
        from risingwave_tpu.expr import functions as F

        if ast.name == "between":
            e, lo, hi = (compile_scalar(a, binder) for a in ast.args)
            return E.Between(e, lo, hi)
        if ast.name == "in":
            e = compile_scalar(ast.args[0], binder)
            vals = tuple(
                a.value for a in ast.args[1:] if isinstance(a, P.Literal)
            )
            return E.InList(e, vals)
        if ast.name in AGG_FUNCS or ast.name in EXTENDED_AGGS:
            raise ValueError(f"aggregate {ast.name}() outside GROUP BY select")
        if getattr(ast, "distinct", False):
            raise ValueError(
                f"DISTINCT specified, but {ast.name} is not an "
                "aggregate function"
            )
        if ast.name == "coalesce":
            return F.Coalesce(
                tuple(compile_scalar(a, binder) for a in ast.args)
            )
        if ast.name == "nullif":
            a, b = (compile_scalar(x, binder) for x in ast.args)
            return F.NullIf(a, b)
        if ast.name == "extract":
            field = ast.args[0]
            if not isinstance(field, P.Literal):
                raise ValueError("EXTRACT field must be a name")
            return F.Extract(
                str(field.value).lower(), compile_scalar(ast.args[1], binder)
            )
        if ast.name == "date_trunc":
            field = ast.args[0]
            if not isinstance(field, P.Literal):
                raise ValueError("date_trunc field must be a string literal")
            return F.DateTrunc(
                str(field.value).lower(), compile_scalar(ast.args[1], binder)
            )
        if F.lookup(ast.name) is not None:
            return F.Func(
                ast.name, tuple(compile_scalar(a, binder) for a in ast.args)
            )
        raise ValueError(f"unknown function {ast.name!r}")
    if isinstance(ast, (P.Exists, P.InSubquery)):
        raise NotImplementedError(
            "EXISTS/IN subqueries are decorrelated only in the TOP-"
            "level WHERE — lift the enclosing derived table into its "
            "own MV (MV-on-MV) to use one inside"
        )
    raise TypeError(f"cannot compile {ast!r}")


def _is_agg(ast) -> bool:
    return isinstance(ast, P.FuncCall) and (
        ast.name in AGG_FUNCS
        or ast.name in EXTENDED_AGGS
        or ast.name in DISTINCT_AGGS
    )


def _contains_agg(ast) -> bool:
    if _is_agg(ast):
        return True
    if isinstance(ast, P.BinaryOp):
        return _contains_agg(ast.left) or _contains_agg(ast.right)
    if isinstance(ast, P.UnaryOp):
        return _contains_agg(ast.operand)
    return False


def _and_all(conjuncts):
    out = None
    for c in conjuncts:
        out = c if out is None else P.BinaryOp("and", out, c)
    return out


def _split_and(e) -> List[object]:
    """Flatten AND-ed conjuncts."""
    if isinstance(e, P.BinaryOp) and e.op == "and":
        return _split_and(e.left) + _split_and(e.right)
    return [e]


def _idents_in_select(select: P.Select):
    """Column references in select items + GROUP BY (not WHERE)."""
    for item in select.items:
        yield from _idents_in(item.expr)
    for g in select.group_by:
        yield g


def _idents_in(ast):
    """Yield every column reference in a scalar AST."""
    if isinstance(ast, P.Ident):
        yield ast
    elif isinstance(ast, P.UnaryOp):
        yield from _idents_in(ast.operand)
    elif isinstance(ast, P.BinaryOp):
        yield from _idents_in(ast.left)
        yield from _idents_in(ast.right)
    elif isinstance(ast, P.CaseExpr):
        for c, v in ast.branches:
            yield from _idents_in(c)
            yield from _idents_in(v)
        if ast.default is not None:
            yield from _idents_in(ast.default)
    elif isinstance(ast, P.FuncCall):
        for a in ast.args:
            if not isinstance(a, str):
                yield from _idents_in(a)


class StreamPlanner:
    def __init__(self, catalog: Catalog, capacity: int = 1 << 14):
        self.catalog = catalog
        self.capacity = capacity
        self._ids = 0

    def _tid(self, mv: str, what: str) -> str:
        self._ids += 1
        return f"{mv}.{what}{self._ids}"

    # -- entry -----------------------------------------------------------
    def plan(self, sql: str) -> PlannedMV:
        stmt = P.parse(sql)
        eowc = False
        if isinstance(stmt, P.CreateMaterializedView):
            name, select = stmt.name, stmt.select
            eowc = stmt.emit_on_window_close
        else:
            name, select = "anon_mv", stmt
        if isinstance(select, P.UnionAll):
            if eowc:
                raise NotImplementedError(
                    "EMIT ON WINDOW CLOSE over UNION ALL unsupported"
                )
            return self._plan_union(name, select)
        # type-directed pass first (decimal literal scaling, dictionary
        # collation guards), then logical optimization (predicate
        # pushdown into derived tables, outer-join simplification,
        # constant folding) — then lower the optimized AST as before
        from risingwave_tpu.sql.optimizer import optimize_select
        from risingwave_tpu.sql.typing import typecheck_select

        select = self._decorrelate(select)
        select = typecheck_select(
            select, self.catalog, getattr(self, "strings", None)
        )
        select = optimize_select(select, catalog=self.catalog)
        select = self._rewrite_distinct(select)
        if select.having is not None and not select.group_by:
            raise ValueError("HAVING requires GROUP BY")
        planned = self._try_over_window_to_topn(name, select)
        if planned is None and isinstance(select.from_, P.Join):
            if select.from_.join_type.startswith("temporal"):
                planned = self._plan_temporal(name, select)
            else:
                planned = self._try_delta_join(name, select)
                if planned is None:
                    planned = self._plan_join(name, select)
        elif planned is None:
            planned = self._plan_single(name, select)
        if eowc:
            # EMIT ON WINDOW CLOSE needs a watermark-cleaned windowed
            # plan — silently accepting it on ANY plan shape with no
            # window cleaning would promise a close that never happens
            from risingwave_tpu.executors.hash_agg import HashAggExecutor

            if not any(
                isinstance(ex, HashAggExecutor)
                and ex.window_key is not None
                for ex in planned.pipeline.executors
            ):
                raise ValueError(
                    "EMIT ON WINDOW CLOSE requires a windowed GROUP BY "
                    "over a WATERMARK-declared relation"
                )
        return planned

    def _plan_union(self, name: str, union: P.UnionAll) -> PlannedMV:
        """UNION ALL: each branch lowers to a hidden MV; the top MV's
        fragment subscribes to ALL of them (the runtime's multi-
        subscription IS the UnionExecutor, union.rs — chunks from
        every upstream merge into one stream) and keys rows by a fresh
        union-level row id so branch ids can never collide.

        v1 scope: branches must be APPEND-ONLY projections with
        identical output schemas — a retracting branch (aggregates,
        TopN) would delete against the fresh row ids and miss."""
        import dataclasses as _dc

        aux: List[PlannedMV] = []
        out_schema: Optional[Dict[str, object]] = None
        added: List[str] = []
        try:
            for i, sel in enumerate(union.selects):
                # a per-branch tag column: the top MV keys rows by
                # (_ubranch, _row_id), so a branch's RETRACTIONS hit
                # exactly the rows that branch inserted (a fresh
                # union-level row id could never be re-derived for a
                # delete)
                sel = _dc.replace(
                    sel,
                    items=tuple(sel.items)
                    + (P.SelectItem(P.Literal(i), "_ubranch"),),
                )
                sub = self._plan_branch(f"__u{i}_{name}", sel)
                if "_row_id" not in sub.schema or sub.mview.pk != (
                    "_row_id",
                ):
                    raise NotImplementedError(
                        "UNION ALL branches must be append-only "
                        "projections (no aggregates/TopN) in this build"
                    )
                sch = tuple(
                    (c, d)
                    for c, d in sub.schema.items()
                    if c not in ("_row_id", "_ubranch")
                )
                if out_schema is None:
                    out_schema = sch
                elif out_schema != sch:
                    # ORDER matters too: name-based merging of swapped
                    # columns would silently diverge from SQL's
                    # positional semantics
                    raise ValueError(
                        "UNION ALL branches must have identical "
                        f"schemas (names, types, AND order): "
                        f"{[c for c, _ in out_schema]} vs "
                        f"{[c for c, _ in sch]}"
                    )
                self.catalog.add_mv(sub)
                added.append(sub.name)
                aux.append(sub)
        except BaseException:
            # a failed later branch must not leak earlier hidden MVs
            # into the catalog (they have no runtime fragment yet)
            for n in added:
                self.catalog.mvs.pop(n, None)
                self.catalog.tables.pop(n, None)
            raise
        cols = tuple(c for c, _ in out_schema)
        mview = MaterializeExecutor(
            pk=("_ubranch", "_row_id"),
            columns=cols,
            table_id=f"{name}.mview",
        )
        pipeline = Pipeline([mview])
        return PlannedMV(
            name,
            pipeline,
            mview,
            {a.name: "single" for a in aux},
            schema={
                **dict(out_schema),
                "_ubranch": jnp.dtype(jnp.int64),
                "_row_id": jnp.dtype(jnp.int64),
            },
            aux=tuple(aux),
        )

    def _plan_branch(self, name: str, select: P.Select) -> PlannedMV:
        """One union branch through the full single-select pipeline
        (typecheck, optimize, lowering)."""
        from risingwave_tpu.sql.optimizer import optimize_select
        from risingwave_tpu.sql.typing import typecheck_select

        select = self._decorrelate(select)
        select = typecheck_select(
            select, self.catalog, getattr(self, "strings", None)
        )
        select = optimize_select(select, catalog=self.catalog)
        select = self._rewrite_distinct(select)
        if isinstance(select.from_, P.Join):
            return self._plan_join(name, select)
        return self._plan_single(name, select)

    @staticmethod
    def _rewrite_distinct(select: P.Select) -> P.Select:
        """SELECT DISTINCT a, b == GROUP BY a, b with no aggregates
        (the reference planner's rewrite) — applied at every nesting
        level (derived tables included)."""
        if not select.distinct:
            return select
        import dataclasses

        if select.group_by or any(_is_agg(it.expr) for it in select.items):
            raise NotImplementedError(
                "DISTINCT with GROUP BY/aggregates is not supported"
            )
        for it in select.items:
            if not isinstance(it.expr, P.Ident):
                raise NotImplementedError(
                    "SELECT DISTINCT items must be bare columns"
                )
        return dataclasses.replace(
            select,
            group_by=tuple(it.expr for it in select.items),
            distinct=False,
        )

    # -- single-input ----------------------------------------------------
    def _plan_single(self, name: str, select: P.Select) -> PlannedMV:
        rel = self._plan_rel(name, select)
        mview = self._make_mview(name, rel)
        pipeline = Pipeline(rel.chain + [mview])
        return PlannedMV(
            name, pipeline, mview, {rel.source: "single"}, schema=rel.schema
        )

    def _make_mview(self, name: str, rel):
        """Pick the MV backend: the DEVICE-resident executor when the
        plan provably never delivers a NULL lane to it — the host-map
        executor pulls every flush chunk to the host (~100ms/chunk on
        a tunneled TPU, memory: DeviceMaterializeExecutor docstring),
        so agg MVs like Nexmark q5 must stay in HBM end to end.

        Provably NULL-free today: terminal HashAgg with non-nullable
        group keys and count-only outputs, reached only through
        column-move projects / filters. Everything else keeps the
        host-map executor (its object rows embed None natively)."""
        cols = tuple(c for c in rel.schema if c not in rel.pk)
        if rel.pk and self._device_mv_safe(rel.chain):
            return DeviceMaterializeExecutor(
                pk=rel.pk,
                columns=cols,
                schema_dtypes=rel.schema,
                table_id=f"{name}.mview",
                capacity=self.capacity,
            )
        return MaterializeExecutor(
            pk=rel.pk, columns=cols, table_id=f"{name}.mview"
        )

    @staticmethod
    def _device_mv_safe(chain) -> bool:
        from risingwave_tpu.expr import expr as E

        for ex in reversed(list(chain)):
            if isinstance(ex, FilterExecutor):
                continue  # drops/retracts rows, never adds NULLs
            if isinstance(ex, ProjectExecutor):
                # column moves only — computed expressions could
                # introduce NULL lanes the device MV didn't declare
                if all(
                    isinstance(expr, E.Col) for _, expr in ex.outputs
                ):
                    continue
                return False
            if isinstance(ex, HashAggExecutor):
                return not any(ex.nullable) and all(
                    c.kind in ("count_star", "count") for c in ex.calls
                )
            return False
        return False

    def _from_bound(self, name: str, src) -> BoundRel:
        """FROM clause -> BoundRel (source chain + schema, no select
        logic applied yet)."""
        chain: List[Executor] = []
        alias = None
        if isinstance(src, P.SubQuery):
            inner = self._plan_rel(name, src.select)
            return BoundRel(
                inner.chain, inner.schema, inner.pk, inner.source, src.alias
            )
        if isinstance(src, P.WindowTVF):
            source = src.table.name
            schema = dict(self.catalog.schema_dtypes(source))
            self._maybe_watermark_filter(chain, source, schema)
            chain.append(
                HopWindowExecutor(
                    src.ts_col, src.size_ms, src.slide_ms,
                    out_start="window_start",
                )
            )
            schema["window_start"] = jnp.dtype(jnp.int64)
            # the hop translates the event-time watermark into a
            # window_start watermark (hop_window.py on_watermark), so
            # downstream windowed aggs can clean closed windows
            wm = self.catalog.watermarks.get(source)
            window_col = (
                "window_start"
                if wm is not None and wm[0] == src.ts_col
                else None
            )
            return BoundRel(
                chain, schema, (), source, src.alias,
                window_col=window_col,
            )
        if isinstance(src, P.TableRef):
            source = src.name
            schema = dict(self.catalog.schema_dtypes(source))
            self._maybe_watermark_filter(chain, source, schema)
            # scanning an MV: its change stream carries retractions keyed
            # by the MV pk — downstream state must key the same way
            pk = (
                tuple(self.catalog.mvs[source].mview.pk)
                if self.catalog.is_mv(source)
                else ()
            )
            return BoundRel(chain, schema, pk, source, src.alias)
        raise TypeError(f"unsupported FROM {src!r}")

    def _maybe_watermark_filter(
        self, chain: List[Executor], source: str, schema
    ) -> None:
        """WATERMARK FOR declarations insert a self-driving
        WatermarkFilterExecutor at the scan (watermark_filter.rs:39):
        late rows drop and the generated watermark walks downstream
        every barrier, cleaning windowed state without driver calls."""
        wm = self.catalog.watermarks.get(source)
        if wm is not None and wm[0] in schema:
            from risingwave_tpu.executors import WatermarkFilterExecutor

            chain.append(WatermarkFilterExecutor(wm[0], lag_ms=wm[1]))

    def _plan_rel(
        self, name: str, select: P.Select, pre: Optional[BoundRel] = None
    ) -> BoundRel:
        """Plan one select over a single (possibly windowed) input.
        ``pre`` overrides FROM processing with an already-bound input
        (the temporal-join path enriches the stream first)."""
        select = self._rewrite_distinct(select)
        if select.having is not None and not select.group_by:
            raise ValueError("HAVING requires GROUP BY")
        bound = pre if pre is not None else self._from_bound(name, select.from_)
        chain = bound.chain
        schema = bound.schema
        pk = bound.pk
        source = bound.source
        alias = bound.alias

        binder = Binder(schema, alias)
        if select.where is not None:
            chain.append(FilterExecutor(compile_scalar(select.where, binder)))

        if any(isinstance(it.expr, P.WindowFuncCall) for it in select.items):
            if select.group_by or select.having is not None:
                raise NotImplementedError(
                    "window functions cannot mix with GROUP BY/HAVING "
                    "in one SELECT (plan as MV-on-MV)"
                )
            chain2, out_schema, pk = self._plan_over_window(
                name, select, binder, schema, pk
            )
            chain.extend(chain2)
            return self._maybe_topn(
                name, select, binder,
                BoundRel(chain, out_schema, pk, source, alias),
            )

        if select.group_by:
            # a windowed input over a watermark-declared relation:
            # grouped aggs keyed on the window column clean closed
            # windows (state_table watermark state cleaning; EMIT ON
            # WINDOW CLOSE finalizes them silently either way — this
            # build also emits intermediate updates before the close)
            wcol = bound.window_col
            chain2, out_schema, pk = self._plan_groupby(
                name, select, binder, schema, retractable=False,
                window_col=wcol,
            )
            chain.extend(chain2)
            if select.having is not None:
                # HAVING filters the agg's OUTPUT stream (group keys +
                # agg aliases) — never pushed below the agg
                chain.append(
                    FilterExecutor(
                        compile_scalar(
                            select.having, Binder(out_schema, None)
                        )
                    )
                )
            return self._maybe_topn(
                name, select, binder,
                BoundRel(chain, out_schema, pk, source, alias),
            )

        if any(_is_agg(it.expr) for it in select.items):
            # no GROUP BY + aggregates -> global SimpleAgg (one row)
            from risingwave_tpu.executors.simple_agg import SimpleAggExecutor

            calls: List[AggCall] = []
            out_schema = {}
            ext_acc = _ext_agg_acc()
            finishing: Dict[str, object] = {}
            dstage, _ = _distinct_dedup_stage(
                select, binder, (), schema, self.capacity,
                self._tid(name, "distinct"),
            )
            chain.extend(dstage)
            for i, item in enumerate(select.items):
                ast = item.expr
                if not _is_agg(ast):
                    raise ValueError(
                        "ungrouped aggregate selects must be all-aggregate"
                    )
                out = item.alias or f"{ast.name}_{i}"
                if ast.args == ("*",):
                    if ast.name != "count":
                        raise ValueError(f"{ast.name}(*) unsupported")
                    calls.append(AggCall("count_star", None, out))
                    out_schema[out] = jnp.dtype(jnp.int64)
                else:
                    arg = ast.args[0]
                    if not isinstance(arg, P.Ident):
                        raise ValueError("aggregate args must be bare columns")
                    incol = binder.resolve(arg)
                    if getattr(ast, "distinct", False) and not _is_distinct_agg(ast):
                        raise NotImplementedError(
                            f"{ast.name}(DISTINCT ...) unsupported"
                        )
                    if _is_distinct_agg(ast):
                        kind = (
                            "count"
                            if ast.name in DISTINCT_AGGS
                            else AGG_FUNCS[ast.name]
                        )
                        calls.append(AggCall(kind, incol, out))
                        out_schema[out] = (
                            jnp.dtype(jnp.int64)
                            if kind == "count"
                            else schema[incol]
                        )
                        continue
                    if ast.name in EXTENDED_AGGS:
                        finishing[out], out_schema[out] = (
                            _lower_extended_agg(ast.name, incol, ext_acc)
                        )
                        continue
                    calls.append(AggCall(AGG_FUNCS[ast.name], incol, out))
                    out_schema[out] = schema[incol]
            calls.extend(ext_acc["calls"])
            pre_cols = ext_acc["pre"]
            agg_schema = schema
            if pre_cols:
                agg_schema = {
                    **schema,
                    **{n: dt for n, (_, dt) in pre_cols.items()},
                }
                chain.append(
                    ProjectExecutor(
                        {
                            **{c: E.col(c) for c in schema},
                            **{n: ex for n, (ex, _) in pre_cols.items()},
                        }
                    )
                )
            chain.append(
                SimpleAggExecutor(
                    tuple(calls), agg_schema, table_id=self._tid(name, "sagg")
                )
            )
            if finishing:
                chain.append(
                    ProjectExecutor(
                        {
                            **{
                                c.output: E.col(c.output)
                                for c in calls
                                if not c.output.startswith("__x")
                            },
                            **finishing,
                        }
                    )
                )
            return BoundRel(chain, out_schema, (), source, alias)

        # no GROUP BY: projection (+ hidden row id when no pk exists)
        outputs: Dict[str, E.Expr] = {}
        out_schema2: Dict[str, object] = {}
        for i, item in enumerate(select.items):
            out = item.alias or (
                item.expr.name if isinstance(item.expr, P.Ident) else f"col{i}"
            )
            outputs[out] = compile_scalar(item.expr, binder)
            if isinstance(item.expr, P.Ident):
                out_schema2[out] = schema[binder.resolve(item.expr)]
            else:
                out_schema2[out] = jnp.dtype(jnp.int64)
        if not pk:
            chain.append(
                RowIdGenExecutor(
                    out_col="_row_id", table_id=self._tid(name, "rowid")
                )
            )
            outputs["_row_id"] = E.col("_row_id")
            out_schema2["_row_id"] = jnp.dtype(jnp.int64)
            pk = ("_row_id",)
        else:
            # an inherited subquery pk must survive the projection or
            # the MV cannot key its rows (join path does the same)
            for pcol in pk:
                if pcol not in outputs:
                    outputs[pcol] = E.col(pcol)
                    out_schema2[pcol] = schema[pcol]
        chain.append(ProjectExecutor(outputs))
        return self._maybe_topn(
            name, select, binder,
            BoundRel(chain, out_schema2, pk, source, alias),
        )

    def _try_over_window_to_topn(
        self, name: str, select: P.Select
    ) -> Optional[PlannedMV]:
        """The reference's over_window_to_topn_rule.rs: rewrite

            SELECT cols FROM (SELECT cols, row_number() OVER
              (PARTITION BY g ORDER BY o [DESC]) AS rn FROM t) AS x
            WHERE rn <= k      (also rn < k, rn = 1)

        onto the retractable GroupTopN executor — per-group top-k
        maintenance is O(changed groups x k) per barrier where the
        general over-window recomputes whole partitions. Returns None
        when the shape doesn't match (the window path handles it)."""
        f = select.from_
        if not (
            isinstance(f, P.SubQuery)
            and isinstance(f.select.from_, (P.TableRef, P.WindowTVF))
            and select.where is not None
            and not select.group_by
            and not select.having
            and select.limit is None
        ):
            return None
        inner = f.select
        if inner.where is not None or inner.group_by or inner.limit:
            return None
        wins = [
            (i, it)
            for i, it in enumerate(inner.items)
            if isinstance(it.expr, P.WindowFuncCall)
        ]
        if len(wins) != 1:
            return None
        wi, witem = wins[0]
        w = witem.expr
        if (
            w.func.name != "row_number"
            or w.frame is not None
            or len(w.order_by) != 1
            or not w.partition_by
        ):
            return None
        rn_name = witem.alias or f"row_number_{wi}"
        # the outer WHERE must be exactly a bound on rn; rn must not be
        # selected (GroupTopN emits rows without a rank column)
        conjs = _split_and(select.where)
        k = None
        for c in conjs:
            if not (
                isinstance(c, P.BinaryOp)
                and isinstance(c.left, P.Ident)
                and c.left.name == rn_name
                and c.left.qualifier in (None, f.alias)
                and isinstance(c.right, P.Literal)
            ):
                return None
            v = c.right.value
            if not isinstance(v, int) or isinstance(v, bool):
                return None  # float/str bounds: the window path filters
            if c.op == "<=":
                bound = v
            elif c.op == "<":
                bound = v - 1
            elif c.op == "=" and v == 1:
                bound = 1
            else:
                return None
            k = bound if k is None else min(k, bound)
        if k is None or k < 1:
            return None
        for it in select.items:
            if not isinstance(it.expr, P.Ident) or it.expr.name == rn_name:
                return None

        bound_rel = self._from_bound(name, inner.from_)
        schema = dict(bound_rel.schema)
        binder = Binder(schema, bound_rel.alias)
        part_cols = tuple(binder.resolve(c) for c in w.partition_by)
        oident, desc = w.order_by[0]
        ocol = binder.resolve(oident)
        chain = list(bound_rel.chain)
        pk = bound_rel.pk
        if not pk:
            chain.append(
                RowIdGenExecutor(
                    out_col="_row_id", table_id=self._tid(name, "rowid")
                )
            )
            schema["_row_id"] = jnp.dtype(jnp.int64)
            pk = ("_row_id",)
        # resolve inner pass-through aliases for the outer projection
        amap = {
            (it.alias or (it.expr.name if isinstance(it.expr, P.Ident) else None)):
                it.expr
            for it in inner.items
        }
        from risingwave_tpu.executors.top_n_plain import (
            RetractableGroupTopNExecutor,
        )

        gt = RetractableGroupTopNExecutor(
            group_by=part_cols,
            order_col=ocol,
            limit=k,
            pk=pk,
            schema_dtypes=schema,
            desc=desc,
            capacity=self.capacity,
            table_id=self._tid(name, "gtopn"),
        )
        chain.append(gt)
        post: Dict[str, E.Expr] = {}
        out_schema: Dict[str, object] = {}
        for it in select.items:
            src = amap.get(it.expr.name)
            if not isinstance(src, P.Ident):
                return None  # inner item is computed: window path
            incol = binder.resolve(src)
            out = it.alias or it.expr.name
            post[out] = E.col(incol)
            out_schema[out] = schema[incol]
        out_pk = []
        for pcol in pk:
            target = pcol
            existing = post.get(pcol)
            if existing is not None and not (
                isinstance(existing, E.Col) and existing.name == pcol
            ):
                # an outer alias SHADOWS the pk name: keying the MV on
                # the aliased values would collide rows — carry the
                # real pk under a hidden name instead
                target = f"_pk_{pcol}"
            post[target] = E.col(pcol)
            out_schema[target] = schema[pcol]
            out_pk.append(target)
        chain.append(ProjectExecutor(post))
        rel = BoundRel(
            chain, out_schema, tuple(out_pk), bound_rel.source,
            bound_rel.alias,
        )
        mview = self._make_mview(name, rel)
        chain.append(mview)
        return PlannedMV(
            name,
            Pipeline(chain),
            mview,
            {bound_rel.source: "single"},
            schema=out_schema,
        )

    def _plan_over_window(
        self, name: str, select: P.Select, binder: Binder,
        schema: Dict[str, object], pk: Tuple[str, ...],
    ):
        """SELECT cols..., fn() OVER (PARTITION BY p ORDER BY o) ... ->
        [RowIdGen] -> Project(needed lanes [+ negated order for DESC])
        -> GeneralOverWindowExecutor -> Project(user columns + pk).

        Reference: binder window_function.rs + the OverWindow plan node
        (general.rs executor). Every call in one SELECT must share one
        window (one PARTITION BY + ORDER BY); frames may differ."""
        from risingwave_tpu.executors.over_window import (
            GeneralOverWindowExecutor,
            WindowCall,
        )

        chain: List[Executor] = []

        # hidden pk for append-only sources (rows need identity so the
        # executor can retract precisely)
        if not pk:
            chain.append(
                RowIdGenExecutor(
                    out_col="_row_id", table_id=self._tid(name, "rowid")
                )
            )
            schema = dict(schema)
            schema["_row_id"] = jnp.dtype(jnp.int64)
            pk = ("_row_id",)

        # group calls by their window spec — one chained executor per
        # distinct (PARTITION BY, ORDER BY), like the reference's
        # multiple OverWindow plan nodes; later executors see earlier
        # outputs as pass-through lanes
        groups: Dict[tuple, dict] = {}
        passthrough: List[Tuple[str, str]] = []  # (out name, in col)
        out_names: List[str] = []
        for i, item in enumerate(select.items):
            ast = item.expr
            if isinstance(ast, P.Ident):
                incol = binder.resolve(ast)
                passthrough.append((item.alias or ast.name, incol))
                continue
            if not isinstance(ast, P.WindowFuncCall):
                raise NotImplementedError(
                    "window SELECTs support bare columns + window "
                    "calls only (wrap computed expressions in a "
                    "derived table)"
                )
            if len(ast.order_by) != 1:
                raise NotImplementedError(
                    "OVER (... ORDER BY) supports exactly one order "
                    "column"
                )
            part_cols = tuple(
                binder.resolve(c) for c in ast.partition_by
            )
            oident, desc = ast.order_by[0]
            ocol = binder.resolve(oident)
            key = (part_cols, ocol, desc)
            g = groups.setdefault(
                key,
                {
                    "part": part_cols,
                    "ocol": ocol,
                    "desc": desc,
                    "eff_ord": (
                        f"_word{len(groups)}" if desc else ocol
                    ),
                    "calls": [],
                },
            )
            out = item.alias or f"{ast.func.name}_{i}"
            out_names.append(out)
            fn, args = ast.func.name, ast.func.args
            if getattr(ast.func, "distinct", False):
                raise NotImplementedError(
                    f"{fn}(DISTINCT ...) OVER (...) unsupported"
                )
            if fn == "row_number":
                g["calls"].append(WindowCall("row_number", None, out))
            elif fn in ("rank", "dense_rank"):
                g["calls"].append(WindowCall(fn, g["eff_ord"], out))
            elif fn == "count" and args == ("*",):
                g["calls"].append(
                    WindowCall("count", None, out, frame=ast.frame)
                )
            elif fn in ("sum", "min", "max"):
                incol = binder.resolve(args[0])
                g["calls"].append(
                    WindowCall(fn, incol, out, frame=ast.frame)
                )
            elif fn in ("lag", "lead"):
                incol = binder.resolve(args[0])
                k = 1
                if len(args) > 1:
                    if not isinstance(args[1], P.Literal):
                        raise ValueError(
                            "lag/lead offset must be a literal"
                        )
                    k = int(args[1].value)
                g["calls"].append(WindowCall(fn, incol, out, offset=k))
            else:
                raise NotImplementedError(
                    f"window function {fn!r} unsupported"
                )

        glist = list(groups.values())
        needed = dict.fromkeys(
            [c for _, c in passthrough]
            + [c for g in glist for c in g["part"]]
            + [g["ocol"] for g in glist]
            + [
                c.input
                for g in glist
                for c in g["calls"]
                if c.input is not None
                and not c.input.startswith("_word")
            ]
            + list(pk)
        )
        pre_outputs: Dict[str, E.Expr] = {c: E.col(c) for c in needed}
        win_schema = {c: schema[c] for c in needed}
        for g in glist:
            if g["desc"]:
                # executors sort ascending: order by the negated lane
                # (ties and rank values are unchanged under negation).
                # Keep the SOURCE dtype: int64 here would truncate a
                # float order column before the executor's own
                # integer-only guard could reject it loudly
                pre_outputs[g["eff_ord"]] = E.lit(0) - E.col(g["ocol"])
                win_schema[g["eff_ord"]] = win_schema[g["ocol"]]
        chain.append(ProjectExecutor(pre_outputs))

        for gi, g in enumerate(glist):
            nullable = tuple(
                c
                for c in win_schema
                if c not in pk
                and c not in g["part"]
                and c != g["eff_ord"]
            )
            chain.append(
                GeneralOverWindowExecutor(
                    partition_by=g["part"],
                    order_col=g["eff_ord"],
                    pk=pk,
                    calls=tuple(g["calls"]),
                    schema_dtypes=dict(win_schema),
                    capacity=self.capacity,
                    nullable=nullable,
                    table_id=self._tid(name, "over"),
                )
            )
            # this group's outputs pass through later executors
            for c in g["calls"]:
                win_schema[c.output] = jnp.dtype(jnp.int64)

        # project down to the user's columns (+ pk identity)
        post: Dict[str, E.Expr] = {}
        out_schema: Dict[str, object] = {}
        for out, incol in passthrough:
            post[out] = E.col(incol)
            out_schema[out] = win_schema[incol]
        for out in out_names:
            post[out] = E.col(out)  # window outputs are int64 lanes
            out_schema[out] = jnp.dtype(jnp.int64)
        for pcol in pk:
            if pcol not in post:
                post[pcol] = E.col(pcol)
                out_schema[pcol] = win_schema[pcol]
        chain.append(ProjectExecutor(post))
        return chain, out_schema, pk

    def _maybe_topn(
        self, name: str, select: P.Select, binder: Binder, rel: BoundRel
    ) -> BoundRel:
        """ORDER BY <col> [DESC] LIMIT n -> retractable TopN maintenance
        (top_n_plain.rs:77). ORDER BY without LIMIT is a no-op for an MV
        (unordered relation), matching the reference planner."""
        if select.limit is None:
            return rel
        if len(select.order_by) != 1:
            raise ValueError(
                "streaming LIMIT needs ORDER BY exactly one column"
            )
        from risingwave_tpu.executors.top_n_plain import TopNExecutor

        ident, desc = select.order_by[0]
        ocol = ident.name if ident.name in rel.schema else None
        if ocol is None:
            raise KeyError(f"ORDER BY column {ident.name!r} not in output")
        rel.chain.append(
            TopNExecutor(
                ocol,
                select.limit,
                rel.pk,
                rel.schema,
                desc=desc,
                capacity=self.capacity,
                table_id=self._tid(name, "topn"),
            )
        )
        return rel

    def _plan_groupby(
        self,
        name: str,
        select: P.Select,
        binder: Binder,
        schema: Dict[str, object],
        retractable: bool,
        nullable_cols: frozenset = frozenset(),
        window_col: Optional[str] = None,
    ):
        """GROUP BY + aggregates (or DISTINCT) over an already-planned
        input with ``schema``. Returns (executors, out_schema, pk).
        ``window_col``: when set AND among the group keys, the agg
        gets window_key state cleaning (closed windows finalize
        silently on the upstream watermark; the MV keeps final rows).

        ``retractable``: the input stream can carry row-level deletes
        (e.g. downstream of a non-append-only join); MIN/MAX calls then
        use materialized-input state (ops/minput.py, minput.rs) instead
        of the append-only latch. ``nullable_cols``: columns that can
        carry SQL NULL (e.g. an outer join's padded side) — group keys
        among them get a NULL group.
        """
        keys = tuple(binder.resolve(g) for g in select.group_by)
        aggs: List[AggCall] = []
        out_schema: Dict[str, object] = {}
        chain: List[Executor] = []
        # DISTINCT aggregates: NULL-filter + dedup on (keys, col) FIRST
        if retractable and any(
            _is_distinct_agg(it.expr) for it in select.items
        ):
            raise NotImplementedError(
                "DISTINCT aggregates need an append-only input"
            )
        dstage, _ = _distinct_dedup_stage(
            select, binder, keys, schema, self.capacity,
            self._tid(name, "distinct"),
        )
        chain.extend(dstage)
        ext_acc = _ext_agg_acc()  # deduped hidden calls + pre inputs
        finishing: Dict[str, object] = {}  # visible out -> Expr over hidden
        for i, item in enumerate(select.items):
            ast = item.expr
            if _is_agg(ast):
                out = item.alias or f"{ast.name}_{i}"
                if ast.args == ("*",):
                    if ast.name != "count":
                        raise ValueError(f"{ast.name}(*) unsupported")
                    aggs.append(AggCall("count_star", None, out))
                    out_schema[out] = jnp.dtype(jnp.int64)
                else:
                    arg = ast.args[0]
                    if not isinstance(arg, P.Ident):
                        raise ValueError(
                            "aggregate args must be bare columns "
                            "(project first)"
                        )
                    incol = binder.resolve(arg)
                    if getattr(ast, "distinct", False) and not _is_distinct_agg(ast):
                        raise NotImplementedError(
                            f"{ast.name}(DISTINCT ...) unsupported"
                        )
                    if _is_distinct_agg(ast):
                        # deduped upstream: the plain kind over unique
                        # rows IS the distinct aggregate (count ->
                        # distinct count, sum -> distinct sum, ...)
                        kind = (
                            "count"
                            if ast.name in DISTINCT_AGGS
                            else AGG_FUNCS[ast.name]
                        )
                        aggs.append(AggCall(kind, incol, out))
                        out_schema[out] = (
                            jnp.dtype(jnp.int64)
                            if kind == "count"
                            else schema[incol]
                        )
                        continue
                    if ast.name in EXTENDED_AGGS:
                        fin, odt = _lower_extended_agg(
                            ast.name, incol, ext_acc
                        )
                        finishing[out] = fin
                        out_schema[out] = odt
                        continue
                    kind = AGG_FUNCS[ast.name]
                    aggs.append(
                        AggCall(
                            kind,
                            incol,
                            out,
                            materialized=retractable
                            and kind in ("min", "max"),
                        )
                    )
                    out_schema[out] = schema[incol]
            elif isinstance(ast, P.Ident):
                colname = binder.resolve(ast)
                if colname not in keys:
                    raise ValueError(
                        f"non-aggregate item {colname!r} not in GROUP BY"
                    )
                out_schema[item.alias or colname] = schema[colname]
            else:
                raise ValueError(
                    "GROUP BY select items must be keys or aggregates"
                )
        renames = {
            binder.resolve(it.expr): it.alias
            for it in select.items
            if isinstance(it.expr, P.Ident) and it.alias
        }
        for c in ext_acc["calls"]:
            aggs.append(
                AggCall(
                    c.kind,
                    c.input,
                    c.output,
                    materialized=retractable and c.kind in ("min", "max"),
                )
            )
        pre_cols = ext_acc["pre"]
        if aggs:
            agg_schema = schema
            if pre_cols:
                # hidden agg inputs (x*x, bool->int) projected in front
                agg_schema = {
                    **schema,
                    **{n: dt for n, (_, dt) in pre_cols.items()},
                }
                chain.append(
                    ProjectExecutor(
                        {
                            **{c: E.col(c) for c in schema},
                            **{n: ex for n, (ex, _) in pre_cols.items()},
                        }
                    )
                )
            chain.append(
                HashAggExecutor(
                    group_keys=keys,
                    calls=tuple(aggs),
                    schema_dtypes=agg_schema,
                    capacity=self.capacity,
                    nullable_keys=tuple(k for k in keys if k in nullable_cols),
                    table_id=self._tid(name, "agg"),
                    # materialized extremes hold DISTINCT values per
                    # group; SQL plans can't bound that statically, so
                    # size generously (the overflow latch still guards)
                    minput_k=256,
                    # watermark-driven state cleaning for windowed
                    # group keys (retention 0, finalize silently: the
                    # MV keeps the closed windows' final rows)
                    window_key=(
                        (window_col, 0, False)
                        if window_col is not None and window_col in keys
                        else None
                    ),
                )
            )
        elif retractable:
            raise ValueError(
                "DISTINCT over a retractable stream needs retractable "
                "dedup (unsupported); add an aggregate"
            )
        else:
            chain.append(
                AppendOnlyDedupExecutor(
                    keys=keys,
                    schema_dtypes=schema,
                    capacity=self.capacity,
                    table_id=self._tid(name, "dedup"),
                )
            )
        visible = [
            a.output for a in aggs if not a.output.startswith("__x")
        ] + list(finishing)
        if finishing:
            # finishing projection: hidden sums/counts -> user values
            chain.append(
                ProjectExecutor(
                    {
                        **{k: E.col(k) for k in keys},
                        **{
                            a.output: E.col(a.output)
                            for a in aggs
                            if not a.output.startswith("__x")
                        },
                        **finishing,
                    }
                )
            )
        if renames:
            chain.append(
                ProjectExecutor(
                    {
                        renames.get(c, c): E.col(c)
                        for c in (list(keys) + visible)
                    }
                )
            )
        pk = tuple(renames.get(k, k) for k in keys)
        if not aggs:
            out_schema = {renames.get(k, k): schema[k] for k in keys}
        else:
            out_schema = {
                **{renames.get(k, k): schema[k] for k in keys},
                **out_schema,
            }
        return chain, out_schema, pk

    # -- joins -----------------------------------------------------------
    def _try_delta_join(
        self, name: str, select: P.Select
    ) -> Optional[PlannedMV]:
        """Plan an INNER 2-way join as a DELTA JOIN over two shared
        CREATE INDEX arrangements (lookup.rs; frontend delta_join
        rule, gated on a session variable like the reference's
        rw_streaming_enable_delta_join). Returns None when the shape
        or the indexes don't fit — the hash join path takes over."""
        if not self.catalog.enable_delta_join:
            return None
        f = select.from_
        if not (
            isinstance(f, P.Join)
            and f.join_type == "inner"
            and isinstance(f.left, P.TableRef)
            and isinstance(f.right, P.TableRef)
        ):
            return None
        if select.where is not None or select.group_by or select.limit:
            return None
        lt, rt = f.left, f.right
        if self.catalog.is_mv(lt.name) or self.catalog.is_mv(rt.name):
            return None
        if lt.name == rt.name:
            # a self-join would collapse the inputs dict to one side;
            # feeding a SHARED arrangement as 'both' would double-count
            return None
        lsch = self.catalog.schema_dtypes(lt.name)
        rsch = self.catalog.schema_dtypes(rt.name)
        lal = {lt.alias or lt.name}
        ral = {rt.alias or rt.name}

        def side_of(ident: P.Ident) -> Optional[str]:
            if ident.qualifier:
                if ident.qualifier in lal:
                    return "l" if ident.name in lsch else None
                if ident.qualifier in ral:
                    return "r" if ident.name in rsch else None
                return None
            inl, inr = ident.name in lsch, ident.name in rsch
            if inl == inr:
                return None  # ambiguous or unknown
            return "l" if inl else "r"

        lkeys, rkeys = [], []
        for c in _split_and(f.on):
            if not (
                isinstance(c, P.BinaryOp)
                and c.op == "="
                and isinstance(c.left, P.Ident)
                and isinstance(c.right, P.Ident)
            ):
                return None
            s1, s2 = side_of(c.left), side_of(c.right)
            if (s1, s2) == ("l", "r"):
                lkeys.append(c.left.name)
                rkeys.append(c.right.name)
            elif (s1, s2) == ("r", "l"):
                lkeys.append(c.right.name)
                rkeys.append(c.left.name)
            else:
                return None
        if not lkeys:
            return None
        if len(set(lkeys)) != len(lkeys) or len(set(rkeys)) != len(
            rkeys
        ):
            # duplicate key columns would collapse under set matching
            # and silently drop a join condition
            return None

        def find_index(table: str, keys: Sequence[str]):
            # EXACT column-set match: lookup() keys its prefix map by
            # the full index-column tuple, so a superset index cannot
            # serve a shorter join key
            for d in self.catalog.indexes.values():
                if d["base"] == table and len(d["cols"]) == len(
                    keys
                ) and set(d["cols"]) == set(keys):
                    return d
            return None

        lidx = find_index(lt.name, lkeys)
        if lidx is None:
            return None
        # permute the key pairs into the LEFT index's column order,
        # then demand a right index with exactly that order
        perm = [lkeys.index(c) for c in lidx["cols"]]
        lkeys = [lkeys[i] for i in perm]
        rkeys = [rkeys[i] for i in perm]
        ridx = next(
            (
                d
                for d in self.catalog.indexes.values()
                if d["base"] == rt.name
                and tuple(d["cols"]) == tuple(rkeys)
            ),
            None,
        )
        if ridx is None:
            return None
        # the seeding/emission paths carry int64 lanes: a float join
        # key or base pk would truncate — decline to the hash path
        for col, sch in [(c, lsch) for c in lkeys + list(
            lidx["base_pk"]
        )] + [(c, rsch) for c in rkeys + list(ridx["base_pk"])]:
            dt = sch.get(col, jnp.dtype(jnp.int64))  # hidden _row_id
            if not jnp.issubdtype(jnp.dtype(dt), jnp.integer):
                return None

        from risingwave_tpu.executors.lookup import DeltaJoinExecutor
        from risingwave_tpu.runtime.pipeline import TwoInputPipeline

        left_out: List[Tuple[str, str]] = []
        right_out: List[Tuple[str, str]] = []
        out_schema: Dict[str, object] = {}
        for i, item in enumerate(select.items):
            ast = item.expr
            if not isinstance(ast, P.Ident):
                return None
            side = side_of(ast)
            if side is None:
                return None
            out = item.alias or ast.name
            (left_out if side == "l" else right_out).append(
                (out, ast.name)
            )
            dt = (lsch if side == "l" else rsch)[ast.name]
            if not jnp.issubdtype(jnp.dtype(dt), jnp.integer):
                # the host delta-join emission path carries int64
                # lanes; a float column would truncate silently —
                # decline, the hash path handles it
                return None
            out_schema[out] = dt
        pk = []
        for i, c in enumerate(lidx["base_pk"]):
            left_out.append((f"_dlpk{i}", c))
            out_schema[f"_dlpk{i}"] = jnp.dtype(jnp.int64)
            pk.append(f"_dlpk{i}")
        for i, c in enumerate(ridx["base_pk"]):
            right_out.append((f"_drpk{i}", c))
            out_schema[f"_drpk{i}"] = jnp.dtype(jnp.int64)
            pk.append(f"_drpk{i}")

        join = DeltaJoinExecutor(
            lidx["arrangement"],
            ridx["arrangement"],
            lkeys,
            rkeys,
            left_out,
            right_out,
        )
        mview = MaterializeExecutor(
            pk=tuple(pk),
            columns=tuple(n for n in out_schema if n not in pk),
            table_id=f"{name}.mview",
        )
        planned = PlannedMV(
            name,
            TwoInputPipeline([], [], join, [mview]),
            mview,
            {lt.name: "left", rt.name: "right"},
            schema=out_schema,
        )
        planned.delta_join = True  # session: seed instead of backfill
        return planned

    def _plan_temporal(self, name: str, select: P.Select) -> PlannedMV:
        """stream JOIN table FOR SYSTEM_TIME AS OF PROCTIME() ON ... —
        the stream side probes the table's materialize state at apply
        time; no join state (temporal_join.rs:44). The probe executor
        joins the left chain, then the ordinary single-input select
        logic (WHERE / GROUP BY / items) runs over the enriched schema.
        """
        from risingwave_tpu.executors.temporal_join import (
            TemporalJoinExecutor,
        )

        join: P.Join = select.from_
        jt = "inner" if join.join_type == "temporal" else "left"
        if not isinstance(join.right, P.TableRef):
            raise ValueError(
                "the temporal side must be a table / MV name"
            )
        rname = join.right.name
        mv = getattr(self, "mviews", {}).get(rname)
        if mv is None and self.catalog.is_mv(rname):
            mv = self.catalog.mvs[rname].mview
        if mv is None:
            raise KeyError(
                f"temporal side {rname!r} is not a materialized relation"
            )
        left = self._from_bound(name, join.left)
        r_alias = join.right.alias or rname
        r_schema = dict(self.catalog.schema_dtypes(rname))
        overlap = set(left.schema) & set(r_schema)
        if overlap:
            raise ValueError(
                f"temporal join sides share column names {overlap}; "
                "alias them apart"
            )

        # ON: left_col = right_pk_col conjuncts, matched to pk order
        pairs: Dict[str, str] = {}

        def walk(e):
            if isinstance(e, P.BinaryOp) and e.op == "and":
                walk(e.left)
                walk(e.right)
                return
            if (
                isinstance(e, P.BinaryOp)
                and e.op == "="
                and isinstance(e.left, P.Ident)
                and isinstance(e.right, P.Ident)
            ):
                a, b = e.left, e.right
                if a.qualifier == r_alias or (
                    a.qualifier is None and a.name in r_schema
                ):
                    a, b = b, a
                if b.name not in mv.pk:
                    raise ValueError(
                        f"temporal ON must match the table pk; {b.name!r} "
                        f"is not in {mv.pk}"
                    )
                pairs[b.name] = a.name
                return
            raise ValueError("temporal ON must be AND-ed equalities")

        walk(join.on)
        if set(pairs) != set(mv.pk):
            raise ValueError(
                f"temporal ON must cover the full pk {mv.pk}, got "
                f"{sorted(pairs)}"
            )
        left_keys = tuple(pairs[k] for k in mv.pk)
        output_cols = tuple(
            c for c in mv.columns if not c.startswith("_")
        )
        tj = TemporalJoinExecutor(
            mv, left_keys, output_cols, join_type=jt
        )
        # mv.columns are expanded LEAF lane names (composite columns
        # decompose); resolve lane dtypes through expand_field, never
        # default silently
        from risingwave_tpu.array.composite import expand_field

        lane_dtypes = {
            ln: jnp.dtype(d)
            for f in self.catalog.tables[rname].fields
            for (ln, d) in expand_field(f)
        }
        schema = dict(left.schema)
        for c in output_cols:
            if c not in lane_dtypes:
                raise KeyError(
                    f"temporal side lane {c!r} has no declared dtype"
                )
            schema[c] = lane_dtypes[c]
        # the enriched row is addressable via either side's qualifier
        quals = frozenset(
            q for q in (left.alias or left.source, r_alias) if q
        )
        enriched = BoundRel(
            left.chain + [tj], schema, left.pk, left.source, quals
        )
        rel = self._plan_rel(name, select, pre=enriched)
        mview = MaterializeExecutor(
            pk=rel.pk,
            columns=tuple(c for c in rel.schema if c not in rel.pk),
            table_id=f"{name}.mview",
        )
        pipeline = Pipeline(rel.chain + [mview])
        return PlannedMV(
            name, pipeline, mview, {rel.source: "single"}, schema=rel.schema
        )

    def _plan_join(self, name: str, select: P.Select) -> PlannedMV:
        import dataclasses as _dc

        aux: List[PlannedMV] = []
        planned = self._plan_join_core(name, select, aux)
        if aux:
            planned = _dc.replace(planned, aux=tuple(aux))
        return planned

    def _lower_nested_join(
        self, name: str, jast: P.Join, aux: List[PlannedMV]
    ) -> BoundRel:
        """Left-deep multi-way joins: plan a NESTED join as a hidden
        MV (``{name}__jK``) and treat its change stream as one input
        of the outer 2-way join — MV-on-MV lowering. The reference
        fragments an n-way join into a tree of 2-way StreamHashJoins
        (optimizer on e2e_test/tpch q3); here the tree edges are the
        runtime's subscription edges."""
        if jast.join_type not in ("inner", "left_semi", "left_anti"):
            raise ValueError(
                "only INNER/SEMI/ANTI nested joins lower to MV trees "
                "(outer nesting unsupported)"
            )
        inner_name = f"{name}__j{len(aux)}"
        # discover the inner result's visible columns + qualifiers with
        # a THROWAWAY binder pass (self._tid stays untouched)
        sides: List[object] = []

        def flat(j):
            if isinstance(j, P.Join):
                flat(j.left)
                flat(j.right)
            else:
                sides.append(j)

        if jast.join_type in ("left_semi", "left_anti"):
            flat(jast.left)  # semi/anti joins emit LEFT columns only
        else:
            flat(jast)
        tmp = StreamPlanner(self.catalog, capacity=self.capacity)
        cols: List[str] = []
        quals: set = set()
        for srel in sides:
            r = tmp._rel_of(inner_name, srel)
            cols.extend(c for c in r.schema if not c.startswith("_"))
            if r.alias:
                quals.add(r.alias)
        inner_sel = P.Select(
            items=tuple(P.SelectItem(P.Ident(c), None) for c in cols),
            from_=jast,
            where=None,
            group_by=(),
        )
        inner = self._plan_join_core(inner_name, inner_sel, aux)
        aux.append(inner)
        self.catalog.add_mv(inner)
        # hidden pk lanes (_row_id) must not collide with the outer
        # side's own hidden lanes: rename them behind a projector
        return self._rename_hidden(
            BoundRel(
                [],
                dict(inner.schema),
                tuple(inner.mview.pk),
                inner_name,
                frozenset(quals | {inner_name}),
            ),
            inner_name,
        )

    def _plan_join_core(
        self, name: str, select: P.Select, aux: List[PlannedMV]
    ) -> PlannedMV:
        join: P.Join = select.from_
        if isinstance(join.left, P.Join):
            left = self._lower_nested_join(name, join.left, aux)
        else:
            left = self._rel_of(name, join.left)
        if isinstance(join.right, P.Join):
            right = self._lower_nested_join(name, join.right, aux)
        else:
            right = self._rel_of(name, join.right)
        # hidden planner lanes (_row_id) may exist on BOTH sides (two
        # non-aggregating derived tables); rename them apart — user
        # columns still must be disjoint, enforced below
        if {c for c in left.schema if c.startswith("_")} & {
            c for c in right.schema if c.startswith("_")
        }:
            left = self._rename_hidden(left, "l")
            right = self._rename_hidden(right, "r")
        if set(left.schema) & set(right.schema):
            raise ValueError(
                f"join sides share column names: "
                f"{set(left.schema) & set(right.schema)} — alias them apart"
            )

        jt = join.join_type
        lkeys, rkeys = self._equi_keys(join.on, left, right)
        hj = HashJoinExecutor(
            left_keys=lkeys,
            right_keys=rkeys,
            left_dtypes=left.schema,
            right_dtypes=right.schema,
            capacity=self.capacity,
            join_type=jt,
            table_id=self._tid(name, "join"),
        )
        # output column set per join type (hash_join.rs:129 variants):
        # semi/anti emit only the driving side; outer joins emit both
        # with the padded side's columns nullable.
        semi_anti = jt.endswith("_semi") or jt.endswith("_anti")
        if semi_anti:
            emit_side = left if jt.startswith("left") else right
            visible = set(emit_side.schema)
        else:
            visible = set(left.schema) | set(right.schema)
        binder = Binder({**left.schema, **right.schema}, None)
        tail: List[Executor] = []
        if select.where is not None:
            for ident in _idents_in(select.where):
                n = self._join_resolve(ident, left, right)
                if n not in visible:
                    raise ValueError(
                        f"WHERE references {n!r}, not emitted by a {jt} join"
                    )
            tail.append(FilterExecutor(compile_scalar(select.where, binder)))
        if select.group_by:
            # GROUP BY over the joined stream (the q7 shape;
            # reference optimizer: StreamHashAgg over StreamHashJoin).
            # Join output can retract (deletes / NULL-pad transitions),
            # so MIN/MAX escalate to materialized-input state; inner
            # joins of append-only sides retract too (a dedup upstream
            # or U- pairs), keep it on unconditionally.
            for ident in _idents_in_select(select):
                n = self._join_resolve(ident, left, right)
                if n not in visible:
                    raise ValueError(
                        f"column {n!r} is not emitted by a {jt} join"
                    )
            padded: frozenset = frozenset()
            if jt in ("left", "full"):
                padded |= frozenset(right.schema)
            if jt in ("right", "full"):
                padded |= frozenset(left.schema)
            gchain, gout, gpk = self._plan_groupby(
                name, select, binder, {**left.schema, **right.schema},
                retractable=True, nullable_cols=padded,
            )
            tail.extend(gchain)
            if select.having is not None:
                tail.append(
                    FilterExecutor(
                        compile_scalar(select.having, Binder(gout, None))
                    )
                )
            mview = MaterializeExecutor(
                pk=gpk,
                columns=tuple(c for c in gout if c not in gpk),
                table_id=f"{name}.mview",
            )
            tail.append(mview)
            pipeline = TwoInputPipeline(left.chain, right.chain, hj, tail)
            return PlannedMV(
                name,
                pipeline,
                mview,
                _join_inputs(left.source, right.source),
                schema=gout,
            )
        if not semi_anti and any(
            _contains_agg(it.expr) for it in select.items
        ):
            # GLOBAL aggregate over a joined stream (TPC-H q17's outer
            # ``sum(l_extendedprice) / 7``): SimpleAgg (retraction-safe
            # signed updates) + a post-projection computing arbitrary
            # scalar expressions over the lifted agg outputs
            from risingwave_tpu.executors.simple_agg import (
                SimpleAggExecutor,
            )

            merged = {**left.schema, **right.schema}
            calls: List[AggCall] = []
            agg_schema: Dict[str, object] = {}
            tmp = [0]

            def lift(ast):
                if _is_agg(ast):
                    out = f"__a{tmp[0]}"
                    tmp[0] += 1
                    if ast.args == ("*",):
                        if ast.name != "count":
                            raise ValueError(f"{ast.name}(*) unsupported")
                        calls.append(AggCall("count_star", None, out))
                        agg_schema[out] = jnp.dtype(jnp.int64)
                    else:
                        arg = ast.args[0]
                        if not isinstance(arg, P.Ident):
                            raise ValueError(
                                "aggregate args must be bare columns "
                                "(project first)"
                            )
                        n = self._join_resolve(arg, left, right)
                        if ast.name in EXTENDED_AGGS:
                            raise NotImplementedError(
                                f"{ast.name}() over a joined global "
                                "aggregate: wrap the join in a derived-"
                                "table MV first"
                            )
                        calls.append(AggCall(AGG_FUNCS[ast.name], n, out))
                        agg_schema[out] = merged[n]
                    return P.Ident(out)
                if isinstance(ast, P.BinaryOp):
                    return P.BinaryOp(ast.op, lift(ast.left), lift(ast.right))
                if isinstance(ast, P.UnaryOp):
                    return P.UnaryOp(ast.op, lift(ast.operand))
                if isinstance(ast, P.Literal):
                    return ast
                raise ValueError(
                    "ungrouped join aggregates: items must be aggregate "
                    "expressions"
                )

            lifted = []
            for i, item in enumerate(select.items):
                outn = item.alias or f"col{i}"
                lifted.append((outn, lift(item.expr), item.expr))
            tail.append(
                SimpleAggExecutor(
                    tuple(calls), merged, table_id=self._tid(name, "sagg")
                )
            )
            outputs: Dict[str, E.Expr] = {}
            gout: Dict[str, object] = {}

            def _has_float_lit(a):
                if isinstance(a, P.Literal):
                    return isinstance(a.value, float)
                if isinstance(a, P.BinaryOp):
                    return _has_float_lit(a.left) or _has_float_lit(a.right)
                if isinstance(a, P.UnaryOp):
                    return _has_float_lit(a.operand)
                return False

            for outn, lexpr, orig in lifted:
                outputs[outn] = compile_scalar(
                    lexpr, Binder(agg_schema, None)
                )
                if isinstance(lexpr, P.Ident):
                    gout[outn] = agg_schema[lexpr.name]
                else:
                    gout[outn] = jnp.dtype(
                        jnp.float64 if _has_float_lit(orig) else jnp.int64
                    )
            tail.append(ProjectExecutor(outputs))
            mview = MaterializeExecutor(
                pk=(),
                columns=tuple(gout),
                table_id=f"{name}.mview",
            )
            tail.append(mview)
            pipeline = TwoInputPipeline(left.chain, right.chain, hj, tail)
            return PlannedMV(
                name,
                pipeline,
                mview,
                _join_inputs(left.source, right.source),
                schema=gout,
            )

        out_names = []
        for i, item in enumerate(select.items):
            if not isinstance(item.expr, P.Ident):
                raise ValueError("join select items must be bare columns v0")
            n = self._join_resolve(item.expr, left, right)
            if n not in visible:
                raise ValueError(
                    f"column {n!r} is not emitted by a {jt} join"
                )
            out_names.append((n, item.alias))
        if semi_anti:
            pk = tuple(emit_side.pk)
        else:
            pk = tuple(left.pk) + tuple(right.pk)
        proj = {alias or n: E.col(n) for n, alias in out_names}
        for p in pk:  # pk columns must survive into the MV
            proj.setdefault(p, E.col(p))
        tail.append(ProjectExecutor(proj))
        rename = {n: (alias or n) for n, alias in out_names}
        mview = MaterializeExecutor(
            pk=tuple(rename.get(p, p) for p in pk),
            columns=tuple(
                alias or n for n, alias in out_names
                if (alias or n) not in {rename.get(p, p) for p in pk}
            ),
            table_id=f"{name}.mview",
        )
        tail.append(mview)
        pipeline = TwoInputPipeline(left.chain, right.chain, hj, tail)
        merged = {**left.schema, **right.schema}
        out_schema = {alias or n: merged[n] for n, alias in out_names}
        for p in pk:
            out_schema.setdefault(rename.get(p, p), merged[p])
        return PlannedMV(
            name,
            pipeline,
            mview,
            _join_inputs(left.source, right.source),
            schema=out_schema,
        )

    def _rel_of(self, name: str, rel) -> BoundRel:
        if isinstance(rel, P.SubQuery):
            bound = self._plan_rel(name, rel.select)
            bound.alias = rel.alias
            return bound
        raise TypeError(
            "join sides must be subqueries with explicit columns "
            f"(got {type(rel).__name__})"
        )

    # -- scalar-subquery decorrelation (binder/expr/subquery.rs:22) ------
    def _decorrelate(self, select: P.Select) -> P.Select:
        """Rewrite WHERE conjuncts of the form

            <col> <cmp> (SELECT [k *] agg(c) FROM t WHERE t.key = <outer col>)

        into an INNER join against a hidden grouped-agg derived table
        plus an algebraic predicate (the reference's correlated-apply →
        join rewrite, narrowed to equality correlation + one aggregate).
        ``avg`` splits into sum/count and the comparison is multiplied
        through by the (positive) count and the coefficient denominator
        — exact in the integer lane domain, no division (TPC-H q17's
        ``l_quantity < (SELECT 0.2 * avg(l_quantity) ...)``)."""
        if select.where is None:
            return select
        import dataclasses as _dc

        conjs = _split_and(select.where)
        out_conjs: List[object] = []
        new_from = select.from_
        sq_i = 0
        changed = False
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "<>": "<>"}
        for c in conjs:
            # EXISTS / NOT EXISTS / IN / NOT IN -> left-semi/anti join
            # (binder/expr/subquery.rs Exists + InSubquery rewrites)
            exists = c if isinstance(c, P.Exists) else None
            anti = False
            if (
                isinstance(c, P.UnaryOp)
                and c.op == "not"
                and isinstance(c.operand, P.Exists)
            ):
                exists, anti = c.operand, True
            if exists is not None:
                new_from = self._semi_anti_join(
                    new_from, exists.select, sq_i, anti, in_expr=None
                )
                sq_i += 1
                changed = True
                continue
            insub, neg = (
                (c, False)
                if isinstance(c, P.InSubquery)
                else (c.operand, True)
                if isinstance(c, P.UnaryOp)
                and c.op == "not"
                and isinstance(c.operand, P.InSubquery)
                else (None, False)
            )
            if insub is not None:
                new_from = self._semi_anti_join(
                    new_from,
                    insub.select,
                    sq_i,
                    insub.negated ^ neg,
                    in_expr=insub.expr,
                )
                sq_i += 1
                changed = True
                continue
            sub = None
            if isinstance(c, P.BinaryOp) and c.op in flip:
                if isinstance(c.right, P.ScalarSubQuery) and isinstance(
                    c.left, P.Ident
                ):
                    outer_e, sub, op = c.left, c.right.select, c.op
                elif isinstance(c.left, P.ScalarSubQuery) and isinstance(
                    c.right, P.Ident
                ):
                    outer_e, sub, op = c.right, c.left.select, flip[c.op]
            if sub is None:
                out_conjs.append(c)
                continue
            new_from, pred = self._decorrelate_one(
                new_from, outer_e, op, sub, sq_i
            )
            out_conjs.append(pred)
            sq_i += 1
            changed = True
        if not changed:
            return select
        return _dc.replace(
            select, from_=new_from, where=_and_all(out_conjs)
        )

    def _as_subquery_rel(self, rel):
        """Bare-table outer FROM -> SELECT * derived table (the join
        planner requires subquery sides with explicit columns)."""
        if isinstance(rel, P.TableRef) and rel.name in self.catalog.tables:
            cols = tuple(
                P.SelectItem(P.Ident(c), None)
                for c in self.catalog.schema_dtypes(rel.name)
            )
            return P.SubQuery(
                P.Select(
                    items=cols, from_=rel, where=None, group_by=()
                ),
                rel.alias or rel.name,
            )
        return rel

    def _semi_anti_join(
        self, from_, sub: P.Select, i: int, anti: bool, in_expr
    ):
        """EXISTS/IN subquery -> a left_semi (negated: left_anti) join
        against a hidden derived table projecting the matching key.

        - EXISTS: the subquery's WHERE must carry one ``t.key = outer``
          equality (the correlation); residual conjuncts stay inside.
        - IN: the subquery's single item is the matching column;
          correlation equalities are also honored when present.
        """
        if not isinstance(sub.from_, P.TableRef):
            raise ValueError(
                "EXISTS/IN subquery FROM must be a plain table / MV name"
            )
        if sub.group_by:
            raise ValueError("EXISTS/IN subquery cannot GROUP BY")
        tname = sub.from_.name
        talias = sub.from_.alias or tname
        tcols = set(self.catalog.schema_dtypes(tname))
        # split correlation equalities out of the subquery's WHERE
        corr: List[Tuple[str, P.Ident]] = []
        rest: List[object] = []
        for cj in _split_and(sub.where) if sub.where is not None else []:
            picked = False
            if (
                isinstance(cj, P.BinaryOp)
                and cj.op == "="
                and isinstance(cj.left, P.Ident)
                and isinstance(cj.right, P.Ident)
            ):
                a, b = cj.left, cj.right
                a_in = a.name in tcols and a.qualifier in (None, talias)
                b_in = b.name in tcols and b.qualifier in (None, talias)
                if a_in and not b_in:
                    corr.append((a.name, b))
                    picked = True
                elif b_in and not a_in:
                    corr.append((b.name, a))
                    picked = True
            if not picked:
                rest.append(cj)
        alias = f"__sq{i}"
        items: List[P.SelectItem] = []
        on = None
        if in_expr is not None:
            if len(sub.items) != 1:
                raise ValueError("IN subquery must select one column")
            it = sub.items[0].expr
            if not isinstance(it, P.Ident):
                raise ValueError("IN subquery item must be a bare column")
            if not isinstance(in_expr, P.Ident):
                raise ValueError(
                    "IN lhs must be a bare column (project first)"
                )
            items.append(P.SelectItem(it, f"sq{i}ink"))
            on = P.BinaryOp(
                "=", P.Ident(f"sq{i}ink", alias), in_expr
            )
        elif not corr:
            raise ValueError(
                "EXISTS subquery must correlate on at least one "
                "t.key = outer column equality"
            )
        for j, (inner_key, outer_ident) in enumerate(corr):
            out = f"sq{i}ck{j}"
            items.append(P.SelectItem(P.Ident(inner_key), out))
            eq = P.BinaryOp("=", P.Ident(out, alias), outer_ident)
            on = eq if on is None else P.BinaryOp("and", on, eq)
        where = _and_all(rest)
        sq = P.SubQuery(
            P.Select(
                items=tuple(items), from_=sub.from_, where=where,
                group_by=(),
            ),
            alias,
        )
        return P.Join(
            left=self._as_subquery_rel(from_),
            right=sq,
            on=on,
            join_type="left_anti" if anti else "left_semi",
        )

    def _decorrelate_one(self, from_, outer_e, op, sub: P.Select, i: int):
        from fractions import Fraction

        if not isinstance(sub.from_, P.TableRef):
            raise ValueError(
                "scalar subquery FROM must be a plain table / MV name"
            )
        tname = sub.from_.name
        talias = sub.from_.alias or tname
        tcols = set(self.catalog.schema_dtypes(tname))
        if sub.group_by or len(sub.items) != 1:
            raise ValueError(
                "scalar subquery must select exactly one aggregate"
            )
        # item: agg(c) or <lit> * agg(c) / agg(c) * <lit>
        e = sub.items[0].expr
        coeff = Fraction(1)
        if isinstance(e, P.BinaryOp) and e.op == "*":
            lit, agg = e.left, e.right
            if isinstance(agg, P.Literal):
                lit, agg = agg, lit
            if not isinstance(lit, P.Literal):
                raise ValueError("scalar subquery item must be lit * agg")
            coeff = Fraction(str(lit.value))
            e = agg
        if not (
            isinstance(e, P.FuncCall)
            and e.name in ("avg", "sum", "min", "max")
            and len(e.args) == 1
            and isinstance(e.args[0], P.Ident)
        ):
            raise ValueError(
                "scalar subquery supports [k *] avg/sum/min/max(col)"
            )
        if getattr(e, "distinct", False):
            raise NotImplementedError(
                f"{e.name}(DISTINCT ...) in a scalar subquery is "
                "unsupported (the decorrelation would drop DISTINCT)"
            )
        if coeff <= 0:
            raise ValueError(
                "scalar subquery coefficient must be positive (the "
                "comparison is multiplied through by it)"
            )
        kind, aggcol = e.name, e.args[0].name
        # correlation: exactly one t.key = outer_col equality; remaining
        # conjuncts stay as the subquery's own WHERE
        corr = None
        rest: List[object] = []
        for cj in _split_and(sub.where) if sub.where is not None else []:
            if (
                corr is None
                and isinstance(cj, P.BinaryOp)
                and cj.op == "="
                and isinstance(cj.left, P.Ident)
                and isinstance(cj.right, P.Ident)
            ):
                a, b = cj.left, cj.right
                a_inner = a.name in tcols and a.qualifier in (None, talias)
                b_inner = b.name in tcols and b.qualifier in (None, talias)
                if a_inner and not b_inner:
                    corr = (a.name, b)
                    continue
                if b_inner and not a_inner:
                    corr = (b.name, a)
                    continue
            rest.append(cj)
        if corr is None:
            raise ValueError(
                "scalar subquery must correlate on one t.key = outer "
                "column equality"
            )
        inner_key, outer_corr = corr
        kname, sname, nname = f"__k{i}", f"__s{i}", f"__n{i}"
        alias = f"__sq{i}"
        items = [P.SelectItem(P.Ident(inner_key), kname)]
        if kind == "avg":
            items.append(
                P.SelectItem(P.FuncCall("sum", (P.Ident(aggcol),)), sname)
            )
            items.append(
                P.SelectItem(P.FuncCall("count", (P.Ident(aggcol),)), nname)
            )
        else:
            items.append(
                P.SelectItem(P.FuncCall(kind, (P.Ident(aggcol),)), sname)
            )
        sq_where = _and_all(rest)
        sq_sel = P.Select(
            items=tuple(items),
            from_=sub.from_,
            where=sq_where,
            group_by=(P.Ident(inner_key),),
        )
        new_from = P.Join(
            left=from_,
            right=P.SubQuery(sq_sel, alias),
            on=P.BinaryOp("=", P.Ident(kname, alias), outer_corr),
            join_type="inner",
        )
        p, q = coeff.numerator, coeff.denominator
        lhs: object = outer_e
        if kind == "avg":
            lhs = P.BinaryOp("*", lhs, P.Ident(nname, alias))
        if q != 1:
            lhs = P.BinaryOp("*", lhs, P.Literal(q))
        rhs: object = P.Ident(sname, alias)
        if p != 1:
            rhs = P.BinaryOp("*", P.Literal(p), rhs)
        return new_from, P.BinaryOp(op, lhs, rhs)

    @staticmethod
    def _rename_hidden(rel: BoundRel, tag: str) -> BoundRel:
        hidden = [c for c in rel.schema if c.startswith("_")]
        if not hidden:
            return rel
        ren = {
            c: (f"_{tag}{c}" if c in hidden else c) for c in rel.schema
        }
        proj = ProjectExecutor({ren[c]: E.col(c) for c in rel.schema})
        return BoundRel(
            rel.chain + [proj],
            {ren[c]: d for c, d in rel.schema.items()},
            tuple(ren.get(p, p) for p in rel.pk),
            rel.source,
            rel.alias,
        )

    @staticmethod
    def _alias_match(qual, alias) -> bool:
        """A lowered join side is addressable through ANY of its
        original sides' qualifiers (alias is then a frozenset)."""
        if isinstance(alias, (set, frozenset)):
            return qual in alias
        return qual == alias

    def _join_resolve(self, ident: P.Ident, left: BoundRel, right: BoundRel):
        if (
            self._alias_match(ident.qualifier, left.alias)
            and ident.name in left.schema
        ):
            return ident.name
        if (
            self._alias_match(ident.qualifier, right.alias)
            and ident.name in right.schema
        ):
            return ident.name
        if ident.qualifier is None:
            if (ident.name in left.schema) != (ident.name in right.schema):
                return ident.name
            raise KeyError(f"ambiguous or unknown column {ident.name!r}")
        raise KeyError(f"cannot resolve {ident.qualifier}.{ident.name}")

    def _equi_keys(self, on, left: BoundRel, right: BoundRel):
        """Flatten AND-ed equality conditions into positional key lists."""
        pairs: List[Tuple[str, str]] = []

        def walk(e):
            if isinstance(e, P.BinaryOp) and e.op == "and":
                walk(e.left)
                walk(e.right)
                return
            if (
                isinstance(e, P.BinaryOp)
                and e.op == "="
                and isinstance(e.left, P.Ident)
                and isinstance(e.right, P.Ident)
            ):
                a, b = e.left, e.right
                an = self._join_resolve(a, left, right)
                bn = self._join_resolve(b, left, right)
                if an in left.schema and bn in right.schema:
                    pairs.append((an, bn))
                elif bn in left.schema and an in right.schema:
                    pairs.append((bn, an))
                else:
                    raise ValueError("join condition must cross sides")
                return
            raise ValueError("ON must be AND-ed equality conditions")

        walk(on)
        if not pairs:
            raise ValueError("no equi-join keys found")
        return tuple(p[0] for p in pairs), tuple(p[1] for p in pairs)
