"""Nexmark query pipelines.

Reference queries (e2e_test/nexmark/):
- q5 (hot items): bids per auction per hop window (size 10s, slide 2s),
  then the max-count auction(s) per window. "q5-lite" is the stateful
  core: the hop-window bid count per auction — the HashAgg stage that
  dominates runtime (VERDICT r1 next-step 1).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from risingwave_tpu.executors import (
    HashAggExecutor,
    HopWindowExecutor,
    MaterializeExecutor,
)
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime import Pipeline

Q5_WINDOW_MS = 10_000
Q5_SLIDE_MS = 2_000


@dataclass
class Q5Lite:
    pipeline: Pipeline
    agg: HashAggExecutor
    mview: MaterializeExecutor


def build_q5_lite(
    capacity: int = 1 << 16,
    window_ms: int = Q5_WINDOW_MS,
    slide_ms: int = Q5_SLIDE_MS,
    state_cleaning: bool = True,
) -> Q5Lite:
    """bids -> hop window -> COUNT(*) per (auction, window_start) -> MV.

    With ``state_cleaning``, an event-time watermark issued as
    ``pipeline.watermark("date_time", wm)`` is translated by the hop
    executor into a ``window_start`` watermark, which closes windows
    that can receive no further rows: pending updates are flushed
    downstream, then state is freed silently (EOWC-final — the MV keeps
    closed windows' final counts). Mirrors the reference's
    watermark-driven state cleaning on q5's agg state
    (state_table.rs:1133).
    """
    hop = HopWindowExecutor("date_time", window_ms, slide_ms)
    agg = HashAggExecutor(
        group_keys=("auction", "window_start"),
        calls=(AggCall("count_star", None, "num"),),
        schema_dtypes={
            "auction": jnp.int64,
            "window_start": jnp.int64,
        },
        capacity=capacity,
        # HopWindowExecutor already translates the event-time watermark
        # into a window_start watermark (start >= first_start(wm) for any
        # future row), so windows below it are closed as-is: retention 0
        window_key=("window_start", 0, False) if state_cleaning else None,
    )
    mview = MaterializeExecutor(
        pk=("auction", "window_start"), columns=("num",)
    )
    return Q5Lite(Pipeline([hop, agg, mview]), agg, mview)
