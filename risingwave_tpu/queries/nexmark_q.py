"""Nexmark query pipelines.

Reference queries (e2e_test/nexmark/):
- q5 (hot items): bids per auction per hop window (size 10s, slide 2s),
  then the max-count auction(s) per window. "q5-lite" is the stateful
  core: the hop-window bid count per auction — the HashAgg stage that
  dominates runtime (VERDICT r1 next-step 1).
- q8 (monitor new users): persons who opened auctions in the same 10s
  tumble window — per-side tumble + DISTINCT, then a stream-stream
  INNER join on (person.id, window) = (auction.seller, window)
  (e2e_test/nexmark/ q8 .slt).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from risingwave_tpu.executors import AppendOnlyDedupExecutor, DynamicMaxFilterExecutor, HashAggExecutor, HashJoinExecutor, HopWindowExecutor
from risingwave_tpu.executors.materialize import DeviceMaterializeExecutor
from risingwave_tpu.ops.agg import AggCall
from risingwave_tpu.runtime import Pipeline, TwoInputPipeline

Q5_WINDOW_MS = 10_000
Q5_SLIDE_MS = 2_000
Q8_WINDOW_MS = 10_000


@dataclass
class Q5Lite:
    pipeline: Pipeline
    agg: HashAggExecutor
    mview: object  # Materialize or DeviceMaterialize


def build_q5_lite(
    capacity: int = 1 << 16,
    window_ms: int = Q5_WINDOW_MS,
    slide_ms: int = Q5_SLIDE_MS,
    state_cleaning: bool = True,
) -> Q5Lite:
    """bids -> hop window -> COUNT(*) per (auction, window_start) -> MV.

    With ``state_cleaning``, an event-time watermark issued as
    ``pipeline.watermark("date_time", wm)`` is translated by the hop
    executor into a ``window_start`` watermark, which closes windows
    that can receive no further rows: pending updates are flushed
    downstream, then state is freed silently (EOWC-final — the MV keeps
    closed windows' final counts). Mirrors the reference's
    watermark-driven state cleaning on q5's agg state
    (state_table.rs:1133).
    """
    hop = HopWindowExecutor("date_time", window_ms, slide_ms)
    agg = HashAggExecutor(
        group_keys=("auction", "window_start"),
        calls=(AggCall("count_star", None, "num"),),
        schema_dtypes={
            "auction": jnp.int64,
            "window_start": jnp.int64,
        },
        capacity=capacity,
        table_id="q5.agg",
        # HopWindowExecutor already translates the event-time watermark
        # into a window_start watermark (start >= first_start(wm) for any
        # future row), so windows below it are closed as-is: retention 0
        window_key=("window_start", 0, False) if state_cleaning else None,
    )
    # device-resident MV: the host-map executor pulls every flush chunk
    # over the tunnel (~100ms/chunk); this one stays in HBM end to end
    mview = DeviceMaterializeExecutor(
        pk=("auction", "window_start"),
        columns=("num",),
        schema_dtypes={
            "auction": jnp.int64,
            "window_start": jnp.int64,
            "num": jnp.int64,
        },
        table_id="q5.mview",
        capacity=max(1 << 12, capacity),
    )
    return Q5Lite(Pipeline([hop, agg, mview]), agg, mview)


@dataclass
class Q8:
    pipeline: TwoInputPipeline
    join: HashJoinExecutor
    mview: object  # Materialize or DeviceMaterialize


def build_q8(
    capacity: int = 1 << 14,
    fanout: int = 8,
    out_cap: int = 1 << 14,
    window_ms: int = Q8_WINDOW_MS,
    state_cleaning: bool = True,
) -> Q8:
    """person ⋈ auction per 10s tumble window (the q8 north star).

    Plan (mirrors the reference's stream plan for q8: two tumbles, two
    distinct aggs, one HashJoin):

      person  -> tumble(date_time)  -> DISTINCT(id, name, starttime)   ┐
                                                                        ⋈ inner on
      auction -> tumble(date_time)  -> DISTINCT(seller, astarttime)   ┘ (id,starttime)=(seller,astarttime)
              -> MV pk=(id, starttime)

    Both input streams are append-only, so each DISTINCT is an
    AppendOnlyDedup (the reference's planner makes the same
    specialization). Watermarks on date_time close old windows through
    the hop -> dedup -> join chain.
    """
    person_chain = [
        HopWindowExecutor("date_time", window_ms, window_ms, out_start="starttime"),
        AppendOnlyDedupExecutor(
            keys=("id", "name", "starttime"),
            schema_dtypes={
                "id": jnp.int64,
                "name": jnp.int32,
                "starttime": jnp.int64,
            },
            capacity=capacity,
            window_key=("starttime", 0) if state_cleaning else None,
            table_id="q8.dedup_person",
        ),
    ]
    auction_chain = [
        HopWindowExecutor("date_time", window_ms, window_ms, out_start="astarttime"),
        AppendOnlyDedupExecutor(
            keys=("seller", "astarttime"),
            schema_dtypes={"seller": jnp.int64, "astarttime": jnp.int64},
            capacity=capacity,
            window_key=("astarttime", 0) if state_cleaning else None,
            table_id="q8.dedup_auction",
        ),
    ]
    join = HashJoinExecutor(
        left_keys=("id", "starttime"),
        right_keys=("seller", "astarttime"),
        left_dtypes={
            "id": jnp.int64,
            "name": jnp.int32,
            "starttime": jnp.int64,
        },
        right_dtypes={"seller": jnp.int64, "astarttime": jnp.int64},
        capacity=capacity,
        fanout=fanout,
        out_cap=out_cap,
        window_cols=("starttime", "astarttime") if state_cleaning else None,
        table_id="q8.join",
    )
    mview = DeviceMaterializeExecutor(
        pk=("id", "starttime"),
        columns=("name",),
        schema_dtypes={
            "id": jnp.int64,
            "starttime": jnp.int64,
            "name": jnp.int32,
        },
        table_id="q8.mview",
        capacity=max(1 << 12, capacity),
    )
    pipeline = TwoInputPipeline(person_chain, auction_chain, join, [mview])
    return Q8(pipeline, join, mview)


@dataclass
class Q7:
    pipeline: TwoInputPipeline
    join: HashJoinExecutor
    agg: HashAggExecutor
    mview: object  # Materialize or DeviceMaterialize


def build_q7(
    capacity: int = 1 << 16,
    fanout: int = 4,
    out_cap: int = 1 << 14,
    window_ms: int = 10_000,
    state_cleaning: bool = True,
    agg_capacity: Optional[int] = None,
    filter_capacity: Optional[int] = None,
    bucketed: bool = True,
) -> Q7:
    """Highest bid per 10s tumble window (Nexmark q7, e2e_test/nexmark/).

    Reference plan shape: bids self-join against the per-window MAX
    (dynamic-filter-free formulation):

      bid -> tumble -> (left)  bids keyed (wstart, price)          ┐
                                                                     ⋈ inner on
      bid -> tumble -> MAX(price) per window -> (right) (mwstart,  ┘ (wstart,price)=(mwstart,maxprice)
              maxprice) change stream [U-/U+ on every new max]
          -> MV pk=(wstart, auction, bidder)

    The right side is the RETRACTING input: each new window max emits
    U-(old)/U+(new), which the join turns into delete/insert of the
    matching bid pairs — exercising the join's retraction path end to
    end. Both sides need the SAME bid chunks: drive with
    ``pipeline.push_left(c); pipeline.push_right(c)``.

    With ``state_cleaning``, advance ``pipeline.watermark("date_time",
    max_event_ts)`` every barrier: bid-side state is every bid of every
    OPEN window — watermarks are what keep it bounded (the same
    contract as the reference's watermark state cleaning on q7).
    """
    left_chain = [
        HopWindowExecutor("date_time", window_ms, window_ms, out_start="wstart"),
        # dynamic pre-filter (dynamic_filter.rs analogue): only bids at
        # or above their window's running max can ever match a future
        # max — keeps the join's bid-side state O(maxima chain), not
        # O(bids); see executors/dynamic_filter.py
        DynamicMaxFilterExecutor(
            group_col="wstart",
            value_col="price",
            schema_dtypes={"wstart": jnp.int64, "price": jnp.int64},
            # growth REBUILDS the table at a new capacity, which
            # recompiles every fused program touching it (~30s each on
            # TPU) — callers that know their volume size this up front
            capacity=filter_capacity or max(1 << 10, capacity >> 6),
            window_key=("wstart", 0) if state_cleaning else None,
            table_id="q7.maxfilter",
            # bucketed=False is the legacy unbounded-rehash twin (the
            # RW-E803 wedge class): soak baselines and the analyzer's
            # detection tests build it deliberately
            bucketed=bucketed,
        ),
    ]
    right_chain = [
        HopWindowExecutor("date_time", window_ms, window_ms, out_start="mwstart"),
        HashAggExecutor(
            group_keys=("mwstart",),
            calls=(AggCall("max", "price", "maxprice"),),
            schema_dtypes={"mwstart": jnp.int64, "price": jnp.int64},
            capacity=agg_capacity or max(1 << 12, capacity >> 4),
            window_key=("mwstart", 0, False) if state_cleaning else None,
            table_id="q7.maxagg",
        ),
    ]
    join = HashJoinExecutor(
        left_keys=("wstart", "price"),
        right_keys=("mwstart", "maxprice"),
        left_dtypes={
            "wstart": jnp.int64,
            "price": jnp.int64,
            "auction": jnp.int64,
            "bidder": jnp.int64,
        },
        right_dtypes={"mwstart": jnp.int64, "maxprice": jnp.int64},
        capacity=capacity,
        fanout=fanout,
        out_cap=out_cap,
        # the agg's delta chunks carry a maxprice null lane (all-False
        # here since price is non-null); declare it so the bucket state
        # would round-trip NULLs faithfully if that ever changes
        right_nullable=("maxprice",),
        window_cols=("wstart", "mwstart") if state_cleaning else None,
        table_id="q7.join",
        bucketed=bucketed,
    )
    mview = DeviceMaterializeExecutor(
        pk=("wstart", "auction", "bidder"),
        columns=("price",),
        schema_dtypes={
            "wstart": jnp.int64,
            "auction": jnp.int64,
            "bidder": jnp.int64,
            "price": jnp.int64,
        },
        table_id="q7.mview",
        capacity=max(1 << 12, capacity),
    )
    pipeline = TwoInputPipeline(left_chain, right_chain, join, [mview])
    agg = right_chain[1]
    return Q7(pipeline, join, agg, mview)
