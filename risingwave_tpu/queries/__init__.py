"""Prebuilt Nexmark query pipelines (the BASELINE.md benchmark set).

Until the SQL frontend lands, these builders play the role of the
planner output: hand-assembled executor chains for the Nexmark queries
(reference DDL: e2e_test/nexmark/ *.slt.part).
"""

from risingwave_tpu.queries.nexmark_q import build_q5_lite

__all__ = ["build_q5_lite"]
