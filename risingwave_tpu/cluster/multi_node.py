"""Multi-compute-node cluster: vnode-sharded fragments across N
node processes.

Reference: the multi-CN deployment — HashDataDispatcher crossing node
boundaries over the exchange service (src/stream/src/executor/
dispatch.rs:683 + src/compute/src/rpc/service/exchange_service.rs) with
the meta barrier manager driving every node's control stream
(proto/stream_service.proto InjectBarrier broadcast).

Engine mapping: each compute node runs the SAME DDL and owns the rows
whose DISTRIBUTION-column hash lands on it (``node = hash(dist) %
n``) — the cross-host half of the hash exchange happens at the
meta/frontend role, which splits every pushed chunk by the same
stable hash the storage layer uses, pushes each slice down its node's
wire, and injects barriers on ALL nodes per epoch. With the
distribution column equal to the MV's group/pk key (the reference's
distribution-key contract), per-node MVs hold DISJOINT keys and a
batch query is the concatenation of the nodes' results.

Each node keeps its own state dir (object store); kill -9 of any node
recovers independently through the single-node replay protocol
(cluster/client.py) while the other nodes keep their state.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from risingwave_tpu.cluster.client import ComputeClient
from risingwave_tpu.storage.sstable import key_hashes


class ShardedClusterClient:
    """The meta/frontend role over N compute nodes."""

    def __init__(self, clients: Sequence[ComputeClient]):
        if not clients:
            raise ValueError("need at least one compute node")
        self.nodes: List[ComputeClient] = list(clients)
        self.dist: Dict[str, str] = {}  # table -> distribution column

    @classmethod
    def spawn(cls, n_nodes: int, state_dirs: Sequence[str]):
        if len(state_dirs) != n_nodes:
            raise ValueError("one state dir per node")
        return cls([ComputeClient.spawn(state_dir=d) for d in state_dirs])

    # -- DDL (broadcast) -------------------------------------------------
    def ddl(self, sql: str, distributed_by: Optional[str] = None) -> str:
        """Run DDL on EVERY node. ``distributed_by`` names the routing
        column for a CREATE TABLE (the reference's distribution key);
        MVs grouping/keying by that column then shard exactly."""
        tags = {self.nodes[i].ddl(sql) for i in range(len(self.nodes))}
        if len(tags) != 1:
            raise RuntimeError(f"nodes disagree on DDL: {tags}")
        tag = next(iter(tags))
        if distributed_by is not None:
            import re

            m = re.match(r"(?is)^\s*create\s+table\s+(\w+)", sql)
            if not m:
                raise ValueError("distributed_by applies to CREATE TABLE")
            self.dist[m.group(1)] = distributed_by
        return tag

    # -- data (hash-routed) ----------------------------------------------
    def push_chunk(
        self, table: str, cols: Dict[str, np.ndarray], capacity: int
    ) -> None:
        dcol = self.dist.get(table)
        if dcol is None:
            raise KeyError(
                f"table {table!r} has no distribution column (pass "
                "distributed_by= at CREATE TABLE)"
            )
        n = len(next(iter(cols.values())))
        if n == 0:
            return
        dest = (
            key_hashes([np.asarray(cols[dcol])])
            % np.uint64(len(self.nodes))
        ).astype(np.int64)
        for i, node in enumerate(self.nodes):
            m = dest == i
            if not m.any():
                continue
            part = {k: np.asarray(v)[m] for k, v in cols.items()}
            node.push_chunk(table, part, capacity)

    def barrier(self) -> List[int]:
        """One epoch across the cluster: every node collects + commits
        its barrier (the meta barrier manager's broadcast). A DEAD node
        recovers in place — respawn from its durable state, replay its
        un-durable chunks (client.recover) — while the other nodes'
        state is untouched; the barrier then retries on that node."""
        epochs = []
        for node in self.nodes:
            try:
                if node.sock is None:  # killed: socket torn down
                    raise ConnectionError("node down")
                epochs.append(node.barrier())
            except (ConnectionError, OSError):
                node.recover()
                epochs.append(node.barrier())
        return epochs

    # -- reads (scatter-gather) -------------------------------------------
    def query(
        self, sql: str, order_by: Optional[str] = None, desc: bool = False
    ) -> Dict[str, list]:
        """Run the SELECT on every node and concatenate — exact when
        the MV's key is the distribution column (disjoint shards).
        ``order_by`` re-establishes a global order at the merge (the
        per-node ORDER BY only orders within a shard)."""
        merged: Dict[str, list] = {}
        for node in self.nodes:
            out = node.query(sql)
            for k, v in out.items():
                merged.setdefault(k, []).extend(v)
        if order_by is not None and merged:
            order = np.argsort(
                np.asarray(merged[order_by]), kind="stable"
            )
            if desc:
                order = order[::-1]
            merged = {k: [v[i] for i in order] for k, v in merged.items()}
        return merged

    # -- failure injection / lifecycle ------------------------------------
    def kill9(self, i: int) -> None:
        self.nodes[i].kill9()

    def close(self) -> None:
        for node in self.nodes:
            try:
                node.close()
            except Exception:
                pass
