"""Multi-compute-node cluster: vnode-sharded fragments across N
node processes.

Reference: the multi-CN deployment — HashDataDispatcher crossing node
boundaries over the exchange service (src/stream/src/executor/
dispatch.rs:683 + src/compute/src/rpc/service/exchange_service.rs) with
the meta barrier manager driving every node's control stream
(proto/stream_service.proto InjectBarrier broadcast).

Engine mapping: each compute node runs the SAME DDL and owns the rows
whose DISTRIBUTION-column hash lands on it (``node = hash(dist) %
n``) — the cross-host half of the hash exchange happens at the
meta/frontend role, which splits every pushed chunk by the same
stable hash the storage layer uses, pushes each slice down its node's
wire, and injects barriers on ALL nodes per epoch. With the
distribution column equal to the MV's group/pk key (the reference's
distribution-key contract), per-node MVs hold DISJOINT keys and a
batch query is the concatenation of the nodes' results.

Each node keeps its own state dir (object store); kill -9 of any node
recovers independently through the single-node replay protocol
(cluster/client.py) while the other nodes keep their state.
"""

from __future__ import annotations

import re
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from risingwave_tpu.cluster.client import ComputeClient
from risingwave_tpu.epoch_trace import record_stage
from risingwave_tpu.event_log import EVENT_LOG
from risingwave_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    RetryPolicy,
)
from risingwave_tpu.storage.sstable import key_hashes

#: a node death during push/barrier is transient at the CLUSTER level:
#: recovery respawns it. ConnectionError/OSError = the wire died.
_NODE_TRANSIENT = (ConnectionError, OSError)


class ShardedClusterClient:
    """The meta/frontend role over N compute nodes."""

    def __init__(
        self,
        clients: Sequence[ComputeClient],
        recover_retry: Optional[RetryPolicy] = None,
    ):
        if not clients:
            raise ValueError("need at least one compute node")
        self.nodes: List[ComputeClient] = list(clients)
        # recover-and-retry budget per node death: a node that cannot
        # come back inside the deadline surfaces instead of wedging the
        # barrier forever (respawn itself can transiently fail)
        self.recover_retry = recover_retry or RetryPolicy.from_env(
            max_attempts=3,
            base_backoff_s=0.2,
            max_backoff_s=2.0,
            deadline_s=60.0,
            classify=lambda e: isinstance(e, _NODE_TRANSIENT),
        )
        # per-node breaker: a node that dies-and-fails-recovery
        # repeatedly opens its breaker, and the cluster fails fast on
        # the next barrier instead of burning a full recover budget
        # per epoch against a husk
        self.node_breakers: List[CircuitBreaker] = [
            CircuitBreaker.from_env(f"node{i}")
            for i in range(len(self.nodes))
        ]
        self.dist: Dict[str, str] = {}  # table/MV -> distribution column
        # MVs whose key does NOT contain their base's distribution
        # column: each node holds a PARTIAL group, so concatenating
        # per-node results duplicates groups — query() must refuse
        # instead of silently returning wrong rows
        self._unsafe_mv: Dict[str, str] = {}  # mv -> reason

    @classmethod
    def spawn(cls, n_nodes: int, state_dirs: Sequence[str]):
        if len(state_dirs) != n_nodes:
            raise ValueError("one state dir per node")
        return cls([ComputeClient.spawn(state_dir=d) for d in state_dirs])

    # -- DDL (broadcast) -------------------------------------------------
    def ddl(self, sql: str, distributed_by: Optional[str] = None) -> str:
        """Run DDL on EVERY node. ``distributed_by`` names the routing
        column for a CREATE TABLE (the reference's distribution key);
        MVs grouping/keying by that column then shard exactly."""
        tags = {self.nodes[i].ddl(sql) for i in range(len(self.nodes))}
        if len(tags) != 1:
            raise RuntimeError(f"nodes disagree on DDL: {tags}")
        tag = next(iter(tags))
        if distributed_by is not None:
            m = re.match(r"(?is)^\s*create\s+table\s+(\w+)", sql)
            if not m:
                raise ValueError("distributed_by applies to CREATE TABLE")
            self.dist[m.group(1)] = distributed_by
        self._classify_mv(sql)
        EVENT_LOG.record("ddl", tag=tag, sql=sql.strip()[:200], scope="cluster")
        return tag

    def _classify_mv(self, sql: str) -> None:
        """Track whether a CREATE MATERIALIZED VIEW's key preserves its
        base's distribution column. Groups sharded by a column in their
        GROUP BY stay node-local (the reference's distribution-key
        contract); an MV grouping by anything else holds PARTIAL groups
        per node, and scatter-gather reads would duplicate them."""
        m = re.match(
            r"(?is)^\s*create\s+materialized\s+view\s+(\w+)\s+as\s+(.*)$",
            sql,
        )
        if not m:
            return
        mv, select = m.group(1), m.group(2)
        # re-creating an MV re-classifies it from scratch — a stale
        # unsafe/dist entry from a dropped namesake must not stick
        self._unsafe_mv.pop(mv, None)
        self.dist.pop(mv, None)
        fm = re.search(r"(?is)\bfrom\s+(?:hop\s*\(\s*(\w+)|(\w+))", select)
        if not fm:
            return
        base = fm.group(1) or fm.group(2)
        base_dist = self.dist.get(base)
        if base_dist is None:
            if base in self._unsafe_mv:
                # MV over an already-unsafe MV inherits the problem
                self._unsafe_mv[mv] = f"builds on unsafe MV {base!r}"
            return
        gm = re.search(
            r"(?is)\bgroup\s+by\s+(.+?)(?:\bhaving\b|\border\s+by\b|;|$)",
            select,
        )
        if gm is None:
            # row-preserving MV: rows stay on the node their base row
            # hashed to — concatenation is exact, contract carries over
            self.dist[mv] = base_dist
            return
        group_cols = {c.strip().lower() for c in gm.group(1).split(",")}
        if base_dist.lower() in group_cols:
            self.dist[mv] = base_dist
        else:
            self._unsafe_mv[mv] = (
                f"key ({', '.join(sorted(group_cols))}) does not contain "
                f"{base!r}'s distribution column {base_dist!r}"
            )

    # -- data (hash-routed) ----------------------------------------------
    def push_chunk(
        self, table: str, cols: Dict[str, np.ndarray], capacity: int
    ) -> None:
        dcol = self.dist.get(table)
        if dcol is None:
            raise KeyError(
                f"table {table!r} has no distribution column (pass "
                "distributed_by= at CREATE TABLE)"
            )
        n = len(next(iter(cols.values())))
        if n == 0:
            return
        dest = (
            key_hashes([np.asarray(cols[dcol])])
            % np.uint64(len(self.nodes))
        ).astype(np.int64)
        for i, node in enumerate(self.nodes):
            m = dest == i
            if not m.any():
                continue
            part = {k: np.asarray(v)[m] for k, v in cols.items()}
            try:
                if node.sock is None:  # killed: socket torn down
                    raise ConnectionError("node down")
                node.push_chunk(table, part, capacity)
            except _NODE_TRANSIENT as e:
                # the chunk was never acked, so it is NOT in the
                # replay buffer: recover the node (which replays its
                # pending chunks), then re-push this one
                self._recover_node(
                    i, node, e,
                    lambda: node.push_chunk(table, part, capacity),
                )

    def _recover_node(self, i: int, node: ComputeClient, cause, fn):
        """Shared death handling for push/barrier: ONE ``recovery``
        event per death, then recover+retry bounded by the policy's
        deadline, gated by the node's breaker."""
        br = self.node_breakers[i]
        if not br.allow():
            raise CircuitOpenError(
                f"node{i} breaker is open (repeated failed recoveries); "
                f"last cause: {cause!r}"
            )
        EVENT_LOG.record("recovery", mode="node", node=i, cause=repr(cause))

        def attempt():
            node.recover()
            return fn()

        def on_retry(exc, n):
            # counts every TRANSIENT failure (incl. the giveup's last
            # attempt) — semantic errors (ComputeError) bypass on_retry
            # and must never poison the breaker: the node is alive
            br.record_failure()

        out = self.recover_retry.run(
            attempt, op="node.recover", on_retry=on_retry
        )
        br.record_success()
        return out

    def barrier(self) -> List[int]:
        """One epoch across the cluster: every node collects + commits
        its barrier (the meta barrier manager's broadcast). A DEAD node
        recovers in place — respawn from its durable state, replay its
        un-durable chunks (client.recover) — while the other nodes'
        state is untouched; the barrier then retries on that node,
        bounded by the recover policy's deadline and the node breaker."""
        epochs = []
        for i, node in enumerate(self.nodes):
            t0 = time.perf_counter()
            try:
                if node.sock is None:  # killed: socket torn down
                    raise ConnectionError("node down")
                epochs.append(node.barrier())
            except _NODE_TRANSIENT as e:
                epochs.append(
                    self._recover_node(i, node, e, node.barrier)
                )
            # per-node barrier RTT: the cross-node half of the epoch's
            # stage attribution (wire + that node's full commit)
            record_stage(
                "node_commit",
                (time.perf_counter() - t0) * 1e3,
                fragment=f"node{i}",
            )
        return epochs

    # -- reads (scatter-gather) -------------------------------------------
    def query(
        self, sql: str, order_by: Optional[str] = None, desc: bool = False
    ) -> Dict[str, list]:
        """Run the SELECT on every node and concatenate — exact when
        the MV's key is the distribution column (disjoint shards).
        ``order_by`` re-establishes a global order at the merge (the
        per-node ORDER BY only orders within a shard)."""
        fm = re.search(r"(?is)\bfrom\s+(\w+)", sql)
        if fm and fm.group(1) in self._unsafe_mv:
            # concatenating partial groups would silently return
            # duplicated-group results — refuse loudly instead
            raise ValueError(
                f"cannot scatter-gather query MV {fm.group(1)!r}: "
                f"{self._unsafe_mv[fm.group(1)]}. Re-create the MV "
                "grouping by the distribution column, or query the "
                "nodes individually and merge groups yourself."
            )
        merged: Dict[str, list] = {}
        for node in self.nodes:
            out = node.query(sql)
            for k, v in out.items():
                merged.setdefault(k, []).extend(v)
        if order_by is not None and merged:
            order = np.argsort(
                np.asarray(merged[order_by]), kind="stable"
            )
            if desc:
                order = order[::-1]
            merged = {k: [v[i] for i in order] for k, v in merged.items()}
        return merged

    # -- failure injection / lifecycle ------------------------------------
    def kill9(self, i: int) -> None:
        self.nodes[i].kill9()

    def close(self) -> None:
        for node in self.nodes:
            try:
                node.close()
            except Exception:
                pass
