"""Framed wire protocol for the two-process cluster.

Frame layout (all little-endian):

    4 bytes  header length H
    4 bytes  payload length P
    H bytes  JSON header (utf-8)
    P bytes  payload (Arrow IPC stream for chunk frames, else empty)

Flow control v0 is the synchronous absorb-ack: the sender keeps ONE
chunk in flight and the receiver's ack (which echoes the row count as
``permits``) releases the next — a degenerate form of the reference's
permit channels (src/stream/src/executor/exchange/permit.rs:35-90,
which generalize to a row budget with piggybacked AddPermits). A slow
compute node therefore back-pressures the frontend instead of growing
an unbounded socket buffer.
"""

from __future__ import annotations

import json
import os
import socket
import struct
from typing import Optional, Tuple

_HDR = struct.Struct("<BII")  # codec byte + header len + payload len
_CODEC_JSON = 0
_CODEC_PROTO = 1


def _default_codec() -> int:
    # the IDL (proto/stream_service.proto) is the wire contract;
    # RW_WIRE_CODEC=json keeps the debug-readable header form
    return (
        _CODEC_JSON
        if os.environ.get("RW_WIRE_CODEC", "proto") == "json"
        else _CODEC_PROTO
    )


def send_frame(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    codec = _default_codec()
    if codec == _CODEC_PROTO:
        from risingwave_tpu.cluster.proto_codec import encode_header

        h = encode_header(header)
    else:
        h = json.dumps(header).encode()
    sock.sendall(_HDR.pack(codec, len(h), len(payload)) + h + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("peer closed")
        buf.extend(part)
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Tuple[dict, bytes]:
    codec, hlen, plen = _HDR.unpack(_recv_exact(sock, _HDR.size))
    raw = _recv_exact(sock, hlen)
    if codec == _CODEC_PROTO:
        from risingwave_tpu.cluster.proto_codec import decode_header

        header = decode_header(raw)
    else:
        header = json.loads(raw)
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


class SharedDictionaries(dict):
    """``chunk_from_arrow`` expects ``{column -> StringDictionary}``;
    a session keeps ONE global dictionary for every varchar lane. This
    mapping hands that shared instance to whichever string column asks
    (only string-typed Arrow columns call ``setdefault``)."""

    def __init__(self, shared):
        super().__init__()
        self._shared = shared

    def setdefault(self, key, default=None):
        return self._shared


def chunk_payload(chunk, dictionaries=None) -> bytes:
    """StreamChunk -> Arrow IPC stream bytes (ops lane included)."""
    import io

    import pyarrow as pa

    from risingwave_tpu.array.arrow import chunk_to_arrow

    batch = chunk_to_arrow(chunk, dictionaries=dictionaries, with_ops=True)
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    return sink.getvalue()


def payload_chunk(data: bytes, capacity: Optional[int] = None,
                  dictionaries=None):
    """Arrow IPC stream bytes -> StreamChunk."""
    import io

    import pyarrow as pa

    from risingwave_tpu.array.arrow import chunk_from_arrow

    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        batches = list(r)
    assert len(batches) == 1, "one batch per chunk frame"
    return chunk_from_arrow(
        batches[0], capacity=capacity, dictionaries=dictionaries
    )
