"""Multi-process cluster roles (the reference's 4-role split,
docs/architecture-design.md:9-21, narrowed to two roles for v0):

- ``compute_node``: a process hosting streaming fragments behind a TCP
  control + exchange stream (src/compute/src/server.rs:85;
  exchange over gRPC in the reference,
  src/compute/src/rpc/service/exchange_service.rs:78-146 — here
  length-prefixed frames with Arrow IPC chunk payloads and permit flow
  control, proto/stream_service.proto:116-122 control stream).
- ``ComputeClient`` (meta/frontend side): drives DDL, the data stream,
  and the barrier clock over the wire; detects compute death and runs
  recovery against the SHARED object store (kill -9 the compute
  process, respawn, recover from the last committed epoch).
"""

from risingwave_tpu.cluster.client import ComputeClient  # noqa: F401
