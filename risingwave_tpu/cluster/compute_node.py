"""Compute-node role: streaming fragments behind a TCP wire.

Reference: ``compute_node_serve`` (src/compute/src/server.rs:85) hosts
gRPC Task/Exchange/Stream services; barriers arrive over the meta
control stream (proto/stream_service.proto:116-122
StreamingControlStream) and data over ExchangeService.GetStream with
permit flow control (exchange_service.rs:78-146, permit.rs:35-90).

TPU build v0: ONE duplex TCP connection carries both streams as framed
messages (cluster/wire.py). DDL ships as SQL text (the reference ships
fragment-graph protos; SQL + deterministic planning reaches the same
actors — documented simplification). State persists to the SHARED
object store (``--state-dir``): a kill -9'd node restarts, replays the
DDL log, recovers from the last committed epoch, and the driver-side
client replays uncommitted chunks — the reference's recovery contract
(barrier/recovery.rs:353) across a real process boundary.

Run: ``python -m risingwave_tpu compute-node --port 0 --state-dir DIR``
(prints ``LISTENING <port>`` on stdout so a parent can connect).
"""

from __future__ import annotations

import os
import socket
import sys


def _build_session(state_dir: str):
    from risingwave_tpu.frontend.session import SqlSession
    from risingwave_tpu.runtime.runtime import StreamingRuntime
    from risingwave_tpu.sql import Catalog
    from risingwave_tpu.storage.meta_backup import DDL_PATH
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    store = LocalFsObjectStore(state_dir)
    runtime = StreamingRuntime(store)
    runtime.auto_recover = True
    if store.exists(DDL_PATH):
        session = SqlSession.restore(runtime)
    else:
        session = SqlSession(Catalog({}), runtime)
    return session


def serve(port: int, state_dir: str) -> None:
    from risingwave_tpu.cluster import wire

    session = _build_session(state_dir)
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port))
    srv.listen(1)
    print(f"LISTENING {srv.getsockname()[1]}", flush=True)
    while True:
        conn, _addr = srv.accept()
        try:
            _serve_conn(conn, session)
        except ConnectionError:
            pass  # driver went away; await a reconnect
        finally:
            conn.close()


def _serve_conn(conn: socket.socket, session) -> None:
    from risingwave_tpu import utils_sync_point as sync_point
    from risingwave_tpu.cluster import wire

    shared = getattr(session, "strings", None)
    dicts = wire.SharedDictionaries(shared) if shared is not None else None
    while True:
        header, payload = wire.recv_frame(conn)
        kind = header.get("type")
        try:
            if kind == "ddl":
                _out, tag = session.execute(header["sql"])
                wire.send_frame(conn, {"type": "ok", "tag": tag})
            elif kind == "chunk":
                chunk = wire.payload_chunk(
                    payload,
                    capacity=header.get("capacity"),
                    dictionaries=dicts,
                )
                table = header["table"]
                targets = session.dml._targets.get(table, ())
                if not targets:
                    raise KeyError(f"no consumers for stream {table!r}")
                try:
                    for frag, side in targets:
                        sync_point.hit("compute_push")
                        session.runtime.push(frag, chunk, side)
                except Exception as push_err:
                    # a failure after the first target absorbed rows
                    # would leave the epoch half-applied; roll the WHOLE
                    # epoch back in place (the watchdog's recovery:
                    # rebuild dead actors + restore from last commit) so
                    # state is as-if this chunk never arrived, then
                    # surface the error — the client has not buffered it
                    # yet, and the next barrier reports barrier_failed
                    # so the client replays the epoch's EARLIER chunks.
                    # The flag is session-level (NOT connection-local,
                    # a reconnect must still see barrier_failed) and set
                    # BEFORE the rollback so no window commits the
                    # half-applied state.
                    session._push_rolled_back = True
                    try:
                        session.runtime._auto_recover(push_err)
                    except BaseException:
                        # the rollback itself failed (or escalated after
                        # repeated deterministic faults): in-place state
                        # is unrecoverable — die, so the driver's
                        # respawn + restore + replay path takes over
                        # from the last DURABLE epoch instead of ever
                        # committing the half-applied one
                        os._exit(11)
                    raise push_err
                # permit grant: rows are returned to the sender's
                # budget only after the node ABSORBED them (permit.rs)
                wire.send_frame(
                    conn,
                    {"type": "ack", "permits": int(header.get("rows", 0))},
                )
            elif kind == "barrier":
                # the watchdog may roll a poisoned epoch back in place
                # (auto_recover); the node's chunks come from the WIRE,
                # so it cannot replay them itself — report the rollback
                # honestly and let the driver replay (silently replying
                # barrier_complete would drop the epoch's rows)
                before = session.runtime.auto_recoveries
                session.runtime.barrier()
                session.runtime.wait_checkpoints()
                committed = (
                    session.runtime.mgr.max_committed_epoch
                    if session.runtime.mgr
                    else 0
                )
                if session.runtime.auto_recoveries > before or getattr(
                    session, "_push_rolled_back", False
                ):
                    session._push_rolled_back = False
                    wire.send_frame(
                        conn,
                        {"type": "barrier_failed", "committed": committed},
                    )
                else:
                    wire.send_frame(
                        conn,
                        {
                            "type": "barrier_complete",
                            "epoch": session.runtime.epoch,
                            "committed": committed,
                        },
                    )
            elif kind == "query":
                from decimal import Decimal

                out, tag = session.execute(header["sql"])
                # results are already decoded (strings, NULL as None)
                # by the session's result edge — small enough for JSON;
                # the DATA plane stays Arrow. DECIMALs cross as their
                # exact string form (JSON has no decimal type).
                rows = {
                    k: [
                        None
                        if x is None
                        else str(x)
                        if isinstance(x, Decimal)
                        else (x.item() if hasattr(x, "item") else x)
                        for x in v
                    ]
                    for k, v in out.items()
                }
                wire.send_frame(
                    conn, {"type": "rows", "tag": tag, "data": rows}
                )
            elif kind == "status":
                wire.send_frame(
                    conn,
                    {
                        "type": "status",
                        "committed": (
                            session.runtime.mgr.max_committed_epoch
                            if session.runtime.mgr
                            else 0
                        ),
                    },
                )
            elif kind == "shutdown":
                wire.send_frame(conn, {"type": "ok", "tag": "BYE"})
                sys.exit(0)
            else:
                raise ValueError(f"unknown frame type {kind!r}")
        except ConnectionError:
            raise
        except Exception as e:  # surfaced to the driver, keep serving
            wire.send_frame(conn, {"type": "error", "message": repr(e)})


def run(port: int, state_dir: str, device: str = "cpu") -> None:
    """Shared entry for ``python -m risingwave_tpu compute-node`` and
    direct module execution — ONE place defines the role's setup."""
    import os

    if device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    # cross-process failpoint (the reference's fail::fail_point! over
    # its sync-point sites): RW_TPU_FAULT="<sync_point>:<nth>" arms the
    # named sync point to raise on its nth hit — tests drive exact
    # crash windows in the spawned node without reaching into it
    fault = os.environ.get("RW_TPU_FAULT")
    if fault:
        from risingwave_tpu import utils_sync_point as sync_point

        name, sep, nth_s = fault.rpartition(":")
        if not sep:
            name, nth_s = fault, "1"
        nth = int(nth_s)
        counter = {"n": 0}

        def _trip() -> None:
            counter["n"] += 1
            if counter["n"] == nth:
                raise RuntimeError(f"injected fault at {name} #{nth}")

        sync_point.activate(name, _trip)
    serve(port, state_dir)


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--state-dir", required=True)
    ap.add_argument("--device", choices=["cpu", "tpu"], default="cpu")
    args = ap.parse_args(argv)
    run(args.port, args.state_dir, args.device)


if __name__ == "__main__":
    main()
