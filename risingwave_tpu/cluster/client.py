"""Meta/frontend-side client for a compute-node process.

Plays the reference's meta + frontend roles against one CN
(src/meta/src/barrier/rpc.rs:247 inject over the control stream;
src/rpc_client/ typed clients): drives DDL, streams chunks with permit
flow control, ticks the barrier clock, and — on compute death — drives
recovery: respawn, let the node restore from the shared store, then
replay every chunk not covered by the last committed epoch
(barrier/recovery.rs:353 + exact source-offset resume).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

from risingwave_tpu.cluster import wire
from risingwave_tpu.resilience import RetryPolicy


class ComputeError(RuntimeError):
    """The node rejected a request (application error, NOT a death)."""


#: connect retries: every OSError is transient here (the node is
#: booting; refusal/reset/timeout all mean "not up YET") — bounded by
#: the policy's deadline, the former fixed 50x100ms spin generalized
_CONNECT_POLICY = RetryPolicy(
    max_attempts=60,
    base_backoff_s=0.05,
    max_backoff_s=0.5,
    deadline_s=15.0,
    classify=lambda e: isinstance(e, OSError),
)


class ComputeClient:
    def __init__(self, port: int, proc: Optional[subprocess.Popen] = None,
                 state_dir: Optional[str] = None,
                 env: Optional[dict] = None):
        self.port = port
        self.proc = proc
        self.state_dir = state_dir
        self.env = dict(env or {})  # reproduced on recovery respawns
        self.sock: Optional[socket.socket] = None
        # client-side varchar lanes encode through ONE dictionary (the
        # session-side mirror); the wire itself carries strings
        from risingwave_tpu.array.dictionary import StringDictionary

        self._strings = StringDictionary()
        # replay buffer: [(sealing_epoch | None, table, cols, cap)] —
        # entries get their sealing epoch at the next barrier; entries
        # whose epoch is <= the node's committed frontier are durable
        # and fall out (the exact-offset-resume contract, client side)
        self._pending: List[Tuple[Optional[int], str, dict, int]] = []
        # crash-during-barrier disambiguation: if the node dies between
        # committing and replying, the restored frontier tells us
        # whether the in-flight barrier sealed the epoch-None entries
        self._last_committed = 0
        self._barrier_inflight = False

    # -- lifecycle -------------------------------------------------------
    @classmethod
    def spawn(
        cls, state_dir: str, port: int = 0, env: Optional[dict] = None
    ) -> "ComputeClient":
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "risingwave_tpu.cluster.compute_node",
                "--port",
                str(port),
                "--state-dir",
                state_dir,
                "--device",
                "cpu",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu", **(env or {})},
        )
        line = proc.stdout.readline().strip()
        if not line.startswith("LISTENING"):
            raise RuntimeError(f"compute node failed to start: {line!r}")
        client = cls(int(line.split()[1]), proc, state_dir, env=env)
        client.connect()
        return client

    def connect(self, policy: Optional[RetryPolicy] = None) -> None:
        from risingwave_tpu.resilience import RetryBudgetExceeded

        def attempt():
            s = socket.create_connection(("127.0.0.1", self.port), 5)
            # RPC replies can lag behind jit compiles on the node
            # (~tens of seconds cold): generous per-op timeout, not
            # the connect timeout
            s.settimeout(300)
            self.sock = s

        try:
            (policy or _CONNECT_POLICY).run(attempt, op="node.connect")
        except RetryBudgetExceeded as e:
            raise ConnectionError(
                f"cannot reach compute node :{self.port}"
            ) from e

    def kill9(self) -> None:
        """SIGKILL the node (chaos path; CPU process — never a TPU
        tunnel client)."""
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait()
        if self.sock is not None:
            self.sock.close()
            self.sock = None

    def close(self) -> None:
        try:
            if self.sock is not None:
                wire.send_frame(self.sock, {"type": "shutdown"})
                wire.recv_frame(self.sock)
        except (ConnectionError, OSError):
            pass
        if self.proc is not None:
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.terminate()
        if self.sock is not None:
            self.sock.close()

    # -- RPC surface -----------------------------------------------------
    def _rpc(self, header: dict, payload: bytes = b""):
        wire.send_frame(self.sock, header, payload)
        reply, data = wire.recv_frame(self.sock)
        if reply.get("type") == "error":
            raise ComputeError(reply["message"])
        return reply, data

    def ddl(self, sql: str) -> str:
        reply, _ = self._rpc({"type": "ddl", "sql": sql})
        return reply["tag"]

    def push_chunk(self, table: str, cols: dict, capacity: int) -> None:
        """Send one chunk (numpy column dict; str/object lanes are
        VARCHAR and ship as Arrow strings). Flow control is the
        synchronous absorb-ack — a window of one chunk in flight (the
        reference's permit channels generalize this to a row budget)."""
        import numpy as np

        from risingwave_tpu.array.chunk import StreamChunk

        rows = len(next(iter(cols.values())))
        enc, dicts, nulls = {}, {}, {}
        for k, v in cols.items():
            a = np.asarray(v)
            if a.dtype.kind in ("U", "O"):
                vals = a.tolist()
                isnull = np.array([x is None for x in vals], bool)
                if isnull.any():
                    nulls[k] = isnull  # SQL NULL, not the string "None"
                enc[k] = self._strings.encode(
                    ["" if x is None else str(x) for x in vals]
                )
                dicts[k] = self._strings
            else:
                enc[k] = a
        chunk = StreamChunk.from_numpy(enc, capacity, nulls=nulls or None)
        reply, _ = self._rpc(
            {"type": "chunk", "table": table, "capacity": capacity,
             "rows": rows},
            wire.chunk_payload(chunk, dictionaries=dicts or None),
        )
        assert reply["type"] == "ack"
        self._pending.append((None, table, cols, capacity))

    def _replay(self, entries) -> None:
        """Re-push entries one at a time; each leaves the pending
        buffer only when its replacement is acked (``push_chunk``
        re-appends on ack) — a death mid-replay keeps the tail for the
        next ``recover()`` instead of silently discarding it."""
        for i, (_e, table, cols, capacity) in enumerate(entries):
            try:
                self.push_chunk(table, cols, capacity)
            except BaseException:
                self._pending.extend(entries[i:])
                raise

    def barrier(self, _retried: bool = False) -> int:
        self._barrier_inflight = True
        try:
            reply, _ = self._rpc({"type": "barrier"})
        except ComputeError:
            # the node REPLIED (it is alive) but the barrier errored —
            # the commit may or may not have landed. Reconcile against
            # the live frontier (the same disambiguation recover()
            # uses) so epoch-None entries a landed commit covered are
            # never replayed; if even status() fails, keep the
            # in-flight ambiguity for recover().
            try:
                committed = self.status()
            except (ComputeError, ConnectionError, OSError):
                committed = None
            if committed is not None:
                if committed > self._last_committed:
                    self._pending = [
                        p for p in self._pending if p[0] is not None
                    ]
                self._last_committed = committed
                self._barrier_inflight = False
            raise
        self._barrier_inflight = False
        committed = int(reply["committed"])
        if reply["type"] == "barrier_failed":
            # the node rolled a poisoned epoch back in place; ITS
            # chunks came from this wire, so WE replay everything the
            # frontier does not cover, then retry once
            self._last_committed = committed
            replay = [
                p
                for p in self._pending
                if p[0] is None or p[0] > committed
            ]
            self._pending = []
            self._replay(replay)
            if _retried:
                raise ComputeError("barrier rolled back twice")
            return self.barrier(_retried=True)
        sealed = int(reply["epoch"])
        self._last_committed = committed
        self._pending = [
            (e if e is not None else sealed, t, c, cap)
            for (e, t, c, cap) in self._pending
        ]
        self._pending = [
            p for p in self._pending if p[0] > committed
        ]
        return committed

    def query(self, sql: str) -> Dict[str, list]:
        reply, _ = self._rpc({"type": "query", "sql": sql})
        return reply.get("data", {})

    def status(self) -> int:
        reply, _ = self._rpc({"type": "status"})
        return int(reply["committed"])

    # -- recovery --------------------------------------------------------
    def recover(self) -> None:
        """Respawn a dead node; it restores DDL + state from the shared
        store on boot. Then replay exactly the chunks the restored
        commit frontier does not cover (kill -9 between a commit and
        its reply must not double-apply rows)."""
        if self.state_dir is None:
            raise RuntimeError("no state_dir to recover from")
        fresh = ComputeClient.spawn(self.state_dir, env=self.env)
        self.port, self.proc, self.sock = fresh.port, fresh.proc, fresh.sock
        frontier = self.status()
        if self._barrier_inflight and frontier > self._last_committed:
            # the node died AFTER committing the in-flight barrier but
            # BEFORE replying: the epoch-None entries are durable —
            # replaying them would double-apply their rows
            self._pending = [p for p in self._pending if p[0] is not None]
        self._barrier_inflight = False
        self._last_committed = frontier
        replay = [
            p for p in self._pending if p[0] is None or p[0] > frontier
        ]
        self._pending = []
        self._replay(replay)
