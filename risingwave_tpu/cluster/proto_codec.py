"""Header-dict <-> protobuf codec for the cluster wire.

The in-process API stays the header dict (``{"type": ..., ...}``) so
client/compute_node logic is codec-agnostic; this module maps those
dicts onto the IDL in ``proto/stream_service.proto`` (the committed
gencode is ``stream_service_pb2.py``). The JSON codec remains
selectable for debugging (``RW_WIRE_CODEC=json``)."""

from __future__ import annotations

import json
from typing import Dict

from risingwave_tpu.cluster import stream_service_pb2 as pb

_REQ_KINDS = ("ddl", "chunk", "barrier", "query", "status", "shutdown")

# the EXACT header keys each frame type may carry: a key outside this
# set would silently vanish on the proto wire while round-tripping
# fine under the json debug codec — fail loudly instead
_KNOWN_KEYS = {
    "ddl": {"sql"},
    "chunk": {"table", "capacity", "rows"},
    "barrier": set(),
    "query": {"sql"},
    "status": {"committed"},
    "shutdown": set(),
    "ok": {"tag"},
    "ack": {"permits"},
    "barrier_complete": {"epoch", "committed"},
    "barrier_failed": {"committed"},
    "rows": {"tag", "data"},
    "error": {"message"},
}


def encode_header(header: Dict) -> bytes:
    kind = header["type"]
    extra = set(header) - {"type"} - _KNOWN_KEYS.get(kind, set())
    if extra:
        raise ValueError(
            f"frame {kind!r} carries keys {sorted(extra)} the wire IDL "
            "does not map — extend proto/stream_service.proto first"
        )
    if kind == "status" and "committed" in header:
        # the NAME collides between the status REQUEST (empty probe)
        # and the node's status REPLY; the reply always carries its
        # durable frontier
        m = pb.Response()
        m.node_status.committed = int(header["committed"])
        return m.SerializeToString()
    if kind in _REQ_KINDS:
        m = pb.Request()
        if kind == "ddl":
            m.ddl.sql = header["sql"]
        elif kind == "chunk":
            m.chunk.table = header["table"]
            m.chunk.capacity = int(header.get("capacity") or 0)
            m.chunk.rows = int(header.get("rows") or 0)
        elif kind == "barrier":
            m.barrier.SetInParent()
        elif kind == "query":
            m.query.sql = header["sql"]
        elif kind == "status":
            m.status.SetInParent()
        else:
            m.shutdown.SetInParent()
        return m.SerializeToString()
    m = pb.Response()
    if kind == "ok":
        m.ok.tag = header.get("tag", "")
    elif kind == "ack":
        m.ack.permits = int(header.get("permits", 0))
    elif kind == "barrier_complete":
        m.barrier_complete.epoch = int(header.get("epoch", 0))
        m.barrier_complete.committed = int(header.get("committed", 0))
    elif kind == "barrier_failed":
        m.barrier_failed.committed = int(header.get("committed", 0))
    elif kind == "rows":
        m.rows.tag = header.get("tag", "")
        m.rows.json_rows = json.dumps(header.get("data", {}))
    elif kind == "error":
        m.error.message = header.get("message", "")
    else:
        raise ValueError(f"unknown frame type {kind!r}")
    return m.SerializeToString()


def decode_header(raw: bytes) -> Dict:
    # Requests and Responses share the wire; their oneof field numbers
    # are DISJOINT (1-6 vs 11-17, see the .proto), so whichever parses
    # with a populated oneof is the frame's true type — decoding needs
    # no out-of-band direction
    req = pb.Request()
    req.ParseFromString(raw)
    which = req.WhichOneof("req")
    if which is not None:
        if which == "ddl":
            return {"type": "ddl", "sql": req.ddl.sql}
        if which == "chunk":
            return {
                "type": "chunk",
                "table": req.chunk.table,
                "capacity": req.chunk.capacity or None,
                "rows": req.chunk.rows,
            }
        if which == "query":
            return {"type": "query", "sql": req.query.sql}
        return {"type": which}
    resp = pb.Response()
    resp.ParseFromString(raw)
    which = resp.WhichOneof("resp")
    if which == "ok":
        return {"type": "ok", "tag": resp.ok.tag}
    if which == "ack":
        return {"type": "ack", "permits": resp.ack.permits}
    if which == "barrier_complete":
        return {
            "type": "barrier_complete",
            "epoch": resp.barrier_complete.epoch,
            "committed": resp.barrier_complete.committed,
        }
    if which == "barrier_failed":
        return {
            "type": "barrier_failed",
            "committed": resp.barrier_failed.committed,
        }
    if which == "rows":
        return {
            "type": "rows",
            "tag": resp.rows.tag,
            "data": json.loads(resp.rows.json_rows),
        }
    if which == "node_status":
        return {"type": "status", "committed": resp.node_status.committed}
    if which == "error":
        return {"type": "error", "message": resp.error.message}
    raise ValueError("frame decodes to neither Request nor Response")
