"""End-to-end state integrity: digests, checksums, corruption faults.

The reference's Hummock checksums every SST block (xxhash64 in the
block footer, verified on every read) because LSM state written once
and read for weeks makes silent corruption permanent. This module is
that contract for the TPU port, three layers deep:

1. **Device digests** — an order-insensitive fold over an executor's
   durable state lanes (masked sum/XOR of per-slot uint32 hashes, so
   pow2-lattice padding and slot placement provably cancel out).
   Computed INSIDE the fused barrier programs (rides the existing
   staged int64 scalar lane — zero extra dispatches) and by a
   bit-identical numpy twin on the host, so fused-vs-interpreted
   bit-identity gets a per-barrier digest cross-check for free.
2. **Checksummed storage** — every SST blob/block and the manifest
   carry ``zlib.crc32`` content checksums written at build time and
   verified on every read path (see storage/state_table.py,
   storage/block_sst.py, storage/meta_backup.py).
3. **Quarantine + verified recovery** — a mismatch raises
   ``StateCorruption`` (a RuntimeError sibling of ``DeviceWedged``:
   deliberately NOT OSError/ValueError, so the resilience layer's
   transient-retry classifier never spins on a wrong byte), the
   artifact is copied aside under ``quarantine/`` (never deleted),
   and recovery walks back to the newest manifest whose
   checksum chain fully verifies.

Digest algorithm (the one contract both jax and numpy must honor):

- per lane, slots are split into little-endian uint32 words
  (``bitcast_convert_type`` on device, ``ndarray.view`` on host; bool
  and sub-4-byte ints promote via ``astype(uint32)`` first);
- a per-slot running hash ``h`` mixes the lane-name seed
  (``crc32(name)``) then every word column:
  ``h = (h ^ w) * 0x9E3779B1; h ^= h >> 15`` — strictly uint32
  (the RW-E302 rule: no 64-bit arithmetic in hash paths);
- lanes fold in sorted-name order, dead slots mask to 0, and the
  reduction is (wrapping uint32 sum, uint32 xor) packed as
  ``(sum << 32) | xor`` in one uint64 — commutative over slots, so
  the digest is invariant under rehash, growth and row order.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

GOLD = 0x9E3779B1  # 2**32 / golden ratio — Fibonacci-hash multiplier
MANIFEST_FORMAT = 2
QUARANTINE_PREFIX = "quarantine"
U64_MASK = (1 << 64) - 1


def digest_enabled() -> bool:
    """Manifest-level table digests are opt-in (``RW_STATE_DIGEST=1``):
    they re-read every table at commit (a whole-table device pull +
    store scan), which the truncated tier-1 window cannot afford by
    default. The fused digest LANES are always on — they ride the
    existing scalar read and cost zero extra dispatches."""
    v = os.environ.get("RW_STATE_DIGEST", "")
    return v.strip().lower() not in ("", "0", "off", "false")


class StateCorruption(RuntimeError):
    """A checksum or digest mismatch: the bytes parse but are WRONG.

    RuntimeError on purpose — ``CheckpointManager._read_transient``
    classifies ``(OSError, ValueError)`` as retryable store weather,
    and a wrong byte must never ride that loop (retrying corruption
    burns the budget and then misclassifies the fault). The artifact
    named here has already been copied to ``quarantine/`` when a store
    was at hand (forensics keep the evidence; recovery walks back)."""

    def __init__(
        self,
        artifact: str,
        kind: str,
        detail: str = "",
        expected=None,
        actual=None,
        quarantined: Optional[str] = None,
    ):
        self.artifact = artifact
        self.kind = kind
        self.detail = detail
        self.expected = expected
        self.actual = actual
        self.quarantined = quarantined
        msg = f"state corruption in {artifact!r} [{kind}]"
        if expected is not None or actual is not None:
            msg += f" expected={expected!r} actual={actual!r}"
        if detail:
            msg += f": {detail}"
        if quarantined:
            msg += f" (quarantined at {quarantined!r})"
        super().__init__(msg)


# ---------------------------------------------------------------------------
# host-cost accounting (the <1%-of-barrier budget perf_gate asserts)
# ---------------------------------------------------------------------------

_HOST = {"ms": 0.0, "checks": 0, "corruptions": 0}


def host_ms() -> float:
    """Cumulative host milliseconds spent verifying crcs + folding
    digests since the last ``reset_host_ms()``."""
    return _HOST["ms"]


def reset_host_ms() -> None:
    _HOST["ms"] = 0.0
    _HOST["checks"] = 0


def corruption_count() -> int:
    return _HOST["corruptions"]


def note_corruption(exc: "StateCorruption") -> None:
    _HOST["corruptions"] += 1
    try:
        from risingwave_tpu.event_log import EVENT_LOG

        EVENT_LOG.record(
            "state_corruption",
            artifact=exc.artifact,
            fault=exc.kind,
            quarantined=exc.quarantined,
            detail=exc.detail[:200],
        )
        from risingwave_tpu.metrics import REGISTRY

        REGISTRY.counter("integrity_corruptions_total").inc(
            kind=exc.kind
        )
    except Exception:  # noqa: BLE001 — observability never masks the fault
        pass


# ---------------------------------------------------------------------------
# crc layer
# ---------------------------------------------------------------------------


def crc32_bytes(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def verify_crc(
    data: bytes, expected: int, artifact: str, kind: str = "crc"
) -> None:
    """Verify ``data`` against a build-time crc; raise StateCorruption
    (NOT quarantined here — the caller owns the store handle)."""
    t0 = time.perf_counter()
    got = crc32_bytes(data)
    _HOST["ms"] += (time.perf_counter() - t0) * 1e3
    _HOST["checks"] += 1
    if got != (expected & 0xFFFFFFFF):
        raise StateCorruption(
            artifact, kind, expected=expected, actual=got
        )


def quarantine(store, path: str, data: Optional[bytes] = None) -> Optional[str]:
    """Copy the corrupt artifact aside for forensics — NEVER delete the
    original (walk-back recovery simply stops referencing it). Returns
    the quarantine path, or None when even the copy failed (a dead
    store must not turn detection into a crash)."""
    qpath = f"{QUARANTINE_PREFIX}/{path}"
    try:
        if data is None:
            data = store.read(path)
        store.put(qpath, data)
        return qpath
    except Exception:  # noqa: BLE001
        return None


def raise_corruption(
    store,
    artifact: str,
    kind: str,
    data: Optional[bytes] = None,
    detail: str = "",
    expected=None,
    actual=None,
):
    """Quarantine + event + raise, in one motion (the storage layer's
    single exit ramp for a detected wrong byte)."""
    q = quarantine(store, artifact, data) if store is not None else None
    exc = StateCorruption(
        artifact, kind, detail=detail, expected=expected, actual=actual,
        quarantined=q,
    )
    note_corruption(exc)
    raise exc


# ---------------------------------------------------------------------------
# manifest envelope (format 2): {"format": 2, "crc32": c, "payload": version}
# ---------------------------------------------------------------------------


def encode_manifest(version: dict) -> bytes:
    payload = json.dumps(version, sort_keys=True)
    return json.dumps(
        {
            "format": MANIFEST_FORMAT,
            "crc32": crc32_bytes(payload.encode()),
            "payload": version,
        }
    ).encode()


def decode_manifest(raw: bytes, artifact: str = "MANIFEST") -> dict:
    """Decode + verify a manifest blob. Raises StateCorruption on a
    torn tail (truncated JSON — the mid-write crash window) or a crc
    mismatch. A pre-envelope (format-1) manifest decodes as-is: those
    bytes predate the integrity layer and carry no checksum to hold
    them to."""
    try:
        doc = json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise StateCorruption(
            artifact, "torn-manifest", detail=str(e)
        ) from None
    if (
        isinstance(doc, dict)
        and doc.get("format") == MANIFEST_FORMAT
        and "payload" in doc
    ):
        payload = doc["payload"]
        want = doc.get("crc32")
        t0 = time.perf_counter()
        got = crc32_bytes(json.dumps(payload, sort_keys=True).encode())
        _HOST["ms"] += (time.perf_counter() - t0) * 1e3
        _HOST["checks"] += 1
        if got != want:
            raise StateCorruption(
                artifact, "manifest-crc", expected=want, actual=got
            )
        return payload
    if isinstance(doc, dict) and not any(
        k in doc for k in ("format", "crc32", "payload")
    ):
        return doc  # legacy format-1: no envelope, no checksum
    # envelope fields present but the envelope does not verify as one:
    # a flipped bit in "format" or "payload" must not launder the blob
    # through the legacy path (the storm test's find)
    raise StateCorruption(
        artifact,
        "manifest-format",
        detail="envelope fields present but malformed",
    )


# ---------------------------------------------------------------------------
# digest fold — numpy twin (bit-identical to the jax fold below)
# ---------------------------------------------------------------------------


def lane_seed(name: str) -> int:
    return crc32_bytes(name.encode("utf-8"))


def _np_slot_words(arr: np.ndarray) -> np.ndarray:
    """(capacity, ...) lane -> (capacity, words) little-endian uint32
    view, matching XLA's bitcast_convert_type minor-dim word order."""
    a = np.ascontiguousarray(arr)
    n = a.shape[0] if a.ndim else 0
    if a.dtype == np.bool_ or a.dtype.itemsize < 4:
        a = a.astype(np.uint32)
    if a.ndim > 1:
        a = np.ascontiguousarray(a.reshape(n, -1))
    w = a.view(np.uint32)
    return w.reshape(n, -1)


def _np_mix(h: np.ndarray, w) -> np.ndarray:
    h = (h ^ w) * np.uint32(GOLD)
    return h ^ (h >> np.uint32(15))


def host_digest(lanes: Dict[str, np.ndarray], live=None) -> int:
    """The numpy fold: returns the packed ``(sum<<32)|xor`` digest as a
    python int in [0, 2**64). Bit-identical to ``device_digest``."""
    t0 = time.perf_counter()
    names = sorted(lanes)
    if not names:
        return 0
    first = np.asarray(lanes[names[0]])
    n = first.shape[0] if first.ndim else 0
    h = np.zeros(n, np.uint32)
    for name in names:
        h = _np_mix(h, np.uint32(lane_seed(name)))
        w = _np_slot_words(np.asarray(lanes[name]))
        for j in range(w.shape[1]):
            h = _np_mix(h, w[:, j])
    if live is not None:
        h = np.where(np.asarray(live, dtype=bool), h, np.uint32(0))
    s = int(h.astype(np.uint64).sum()) & 0xFFFFFFFF
    x = int(np.bitwise_xor.reduce(h)) if n else 0
    _HOST["ms"] += (time.perf_counter() - t0) * 1e3
    return (s << 32) | x


def host_rows_digest(
    keys: Dict[str, np.ndarray], values: Dict[str, np.ndarray]
) -> int:
    """Digest of a table's durable ROW IMAGE (what ``read_table``
    returns): the manifest-level digest. Order-insensitive over rows,
    so compaction/merge order cannot move it."""
    lanes = dict(keys)
    lanes.update(values)
    return host_digest(lanes, live=None)


# ---------------------------------------------------------------------------
# digest fold — jax twin (runs INSIDE the fused barrier programs)
# ---------------------------------------------------------------------------


def device_digest(lanes: dict, live=None):
    """The jax fold: same contract as ``host_digest``, returns a ()
    int64 scalar (the uint64 pack bitcast, so it rides the existing
    staged int64 scalar lane unchanged). Decode host-side with
    ``digest_from_scalar``."""
    import jax
    import jax.numpy as jnp

    names = sorted(lanes)
    if not names:
        return jnp.zeros((), jnp.int64)
    first = lanes[names[0]]
    n = first.shape[0] if first.ndim else 0

    def mix(h, w):
        h = (h ^ w) * jnp.uint32(GOLD)
        return h ^ (h >> jnp.uint32(15))

    h = jnp.zeros(n, jnp.uint32)
    for name in names:
        h = mix(h, jnp.uint32(lane_seed(name)))
        a = lanes[name]
        if a.dtype == jnp.bool_ or a.dtype.itemsize < 4:
            a = a.astype(jnp.uint32)
        if a.ndim > 1:
            a = a.reshape(n, -1)
        w = jax.lax.bitcast_convert_type(a, jnp.uint32)
        w = w.reshape(n, -1)
        for j in range(w.shape[1]):
            h = mix(h, w[:, j])
    if live is not None:
        h = jnp.where(live, h, jnp.uint32(0))
    s = jnp.sum(h, dtype=jnp.uint32)
    x = jax.lax.reduce(
        h, jnp.uint32(0), jax.lax.bitwise_xor, (0,)
    )
    packed = (s.astype(jnp.uint64) << jnp.uint64(32)) | x.astype(
        jnp.uint64
    )
    return jax.lax.bitcast_convert_type(packed, jnp.int64)


def digest_from_scalar(v) -> int:
    """Decode a staged int64 digest scalar back to the uint64 domain
    (the host fold's return type) for equality compares."""
    return int(v) & U64_MASK


# ---------------------------------------------------------------------------
# per-executor-kind lane builders — SHARED by the fused programs (jax
# arrays in, device_digest) and the host twins (device buffers viewed
# via np.asarray, host_digest). Coverage contract: DURABLE LOGICAL
# content only — bookkeeping lanes (dirty/sdirty/stored/latches) differ
# legitimately after a restore and are excluded by construction.
# ---------------------------------------------------------------------------


def agg_lanes(table, state) -> Tuple[dict, object]:
    """HashAgg: keys + row_count + accums + nonnull + emitted
    snapshots. Mask = live | emitted_valid (a zero-count group whose
    emitted snapshot still matters keeps its slot)."""
    lanes = {f"k{i}": k for i, k in enumerate(table.keys)}
    lanes["row_count"] = state.row_count
    for nm, a in state.accums.items():
        lanes[f"acc_{nm}"] = a
    for nm, a in state.nonnull.items():
        lanes[f"nn_{nm}"] = a
    for nm, a in state.emitted.items():
        lanes[f"em_{nm}"] = a
    for nm, a in state.emitted_isnull.items():
        lanes[f"ei_{nm}"] = a
    lanes["ev"] = state.emitted_valid
    return lanes, table.live | state.emitted_valid


def mv_lanes(table, state) -> Tuple[dict, object]:
    """Device MV: pk lanes + value lanes + null lanes, live rows."""
    lanes = {f"k{i}": k for i, k in enumerate(table.keys)}
    for nm, a in state.values.items():
        lanes[f"v_{nm}"] = a
    for nm, a in state.vnulls.items():
        lanes[f"n_{nm}"] = a
    return lanes, table.live


def dedup_lanes(table) -> Tuple[dict, object]:
    """Append-only dedup: the seen-set IS the state — just keys."""
    return {f"k{i}": k for i, k in enumerate(table.keys)}, table.live


def filter_lanes(table, maxes) -> Tuple[dict, object]:
    """DynamicMaxFilter: key lanes + per-key max."""
    lanes = {f"k{i}": k for i, k in enumerate(table.keys)}
    lanes["max"] = maxes
    return lanes, table.live


def join_side_lanes(side, where) -> Tuple[dict, object]:
    """One join side: keys + bucket payload rows + degrees, with
    bucket entries masked by ``row_valid`` BEFORE the fold (stale
    bytes in vacated bucket slots must not shift the digest). Pass
    ``jnp.where`` or ``np.where`` as ``where`` — the builder is
    backend-agnostic."""
    lanes = {f"k{i}": k for i, k in enumerate(side.table.keys)}
    rv = side.row_valid
    for nm, a in side.rows.items():
        zero = np.zeros((), np.asarray(a).dtype) if isinstance(
            a, np.ndarray
        ) else a.dtype.type(0)
        lanes[f"r_{nm}"] = where(rv, a, zero)
    for nm, a in side.row_nulls.items():
        lanes[f"rn_{nm}"] = where(rv, a, False)
    lanes["rv"] = rv
    lanes["deg"] = where(rv, side.degree, 0)
    return lanes, side.table.live


def host_obj_digest(obj) -> int:
    """Digest of an arbitrary host-side state object via its canonical
    JSON bytes (sort_keys, default=str). For executors whose state is
    python dicts/scalars rather than device lanes — deterministic, but
    NOT the lane fold (lint's RW-E709 accepts either contract)."""
    t0 = time.perf_counter()
    blob = json.dumps(obj, sort_keys=True, default=str).encode()
    c = crc32_bytes(blob)
    c2 = crc32_bytes(blob[::-1])
    _HOST["ms"] += (time.perf_counter() - t0) * 1e3
    return (c << 32) | c2


def foldable_dtypes(lanes: Dict[str, object]) -> Iterable[str]:
    """Names of lanes whose dtype the fold CANNOT cover (non-numeric,
    object arrays, ...) — the RW-E709 leaf check."""
    bad = []
    for name, a in lanes.items():
        kind = getattr(getattr(a, "dtype", None), "kind", "O")
        if kind not in ("b", "i", "u", "f"):
            bad.append(name)
    return bad
