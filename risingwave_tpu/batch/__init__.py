"""Batch engine — ad-hoc queries over materialized state.

Reference: src/batch/ (21.6k LoC: BatchTaskExecution + executors) and
the local execution mode (docs/batch-local-execution-mode.md) — here
the LOCAL mode only: one-shot queries over MV snapshots.
"""

from risingwave_tpu.batch.engine import BatchQueryEngine

__all__ = ["BatchQueryEngine"]
