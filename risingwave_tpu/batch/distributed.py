"""Distributed batch execution: stage DAG over vnode partitions.

Reference: ``BatchPlanFragmenter`` builds a stage DAG
(src/frontend/src/scheduler/plan_fragmenter.rs:137); each stage runs N
``BatchTaskExecution`` tasks on compute nodes
(src/batch/src/task/task_execution.rs:300) connected by hash-shuffle
channels (task/hash_shuffle_channel.rs); the root streams to the
frontend.

TPU re-design: the "cluster" is one process (as everywhere in this
build), but the EXECUTION MODEL is the reference's: leaf scan tasks
read disjoint vnode partitions of the MV snapshot, a hash shuffle
routes rows to per-task agg/join stages keyed by vnode (the same
``hash_columns % VNODE_COUNT`` routing the streaming exchange uses),
and the gather stage merges per-task outputs. Per-task partials are
combined with the aggregate's combine rule (count/sum add, min/max
extremize) — the classic two-phase batch agg.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from risingwave_tpu.ops.hashing import VNODE_COUNT
from risingwave_tpu.sql import parser as P


def _vnodes(
    cols: Dict[str, np.ndarray], keys: List[str]
) -> Optional[np.ndarray]:
    """Vectorized host-side key partitioning (fmix64-style mixing —
    per-row Python hashing would be interpreter-bound at snapshot
    scale). Deterministic; disjointness is what correctness needs, not
    parity with the device routing. None for non-integer keys (caller
    falls back to local mode)."""
    n = len(next(iter(cols.values()))) if cols else 0
    acc = np.zeros(n, np.uint64)
    with np.errstate(over="ignore"):
        for k in keys:
            lane = np.ascontiguousarray(cols[k])
            if not np.issubdtype(lane.dtype, np.integer):
                return None
            h = lane.astype(np.int64).astype(np.uint64)
            h ^= h >> np.uint64(33)
            h *= np.uint64(0xFF51AFD7ED558CCD)
            h ^= h >> np.uint64(33)
            h *= np.uint64(0xC4CEB9FE1A85EC53)
            h ^= h >> np.uint64(33)
            acc = acc * np.uint64(1099511628211) + h
    return (acc % np.uint64(VNODE_COUNT)).astype(np.int64)


class DistributedBatchRunner:
    """Runs a SELECT as a stage DAG of partition tasks, then checks in
    with the gather stage. Used by BatchQueryEngine when
    ``distributed_tasks`` > 1 (the reference picks distributed mode for
    non-point queries, scheduler/local.rs:60 comment)."""

    def __init__(self, engine, n_tasks: int = 4):
        self.engine = engine
        self.n_tasks = n_tasks

    def query(self, stmt: P.Select) -> Optional[Dict[str, np.ndarray]]:
        """Distributed plan for single-table scans; returns None when
        the shape is not partitionable (the caller falls back to local
        mode, exactly like the reference's local/distributed split)."""
        if not isinstance(stmt.from_, P.TableRef):
            return None
        if stmt.order_by or stmt.limit is not None:
            return None  # root-side sort/limit: keep local mode
        mv = self.engine.tables.get(stmt.from_.name)
        if mv is None:
            return None
        cols = mv.to_numpy()
        n = len(next(iter(cols.values()))) if cols else 0
        if n == 0:
            return None

        has_agg = any(
            isinstance(i.expr, P.FuncCall)
            and i.expr.name in ("count", "sum", "min", "max")
            for i in stmt.items
        )
        # extended aggregates (avg/var/stddev/bool_*) have no partial-
        # merge rule here; grouped ones are exact anyway (hash-disjoint
        # groups, concatenation merges) but GLOBAL ones must run local
        from risingwave_tpu.sql.planner import EXTENDED_AGGS

        from risingwave_tpu.batch.engine import (
            COLLECT_AGGS,
            DISTINCT_AGG_NAMES,
        )

        if not stmt.group_by and any(
            isinstance(i.expr, P.FuncCall)
            and (
                i.expr.name in EXTENDED_AGGS
                or i.expr.name in DISTINCT_AGG_NAMES
                or i.expr.name in COLLECT_AGGS
                or getattr(i.expr, "distinct", False)
            )
            for i in stmt.items
        ):
            return None
        # window functions need the WHOLE partition in one task (row_
        # number over round-robin slices would restart per task):
        # local mode handles them
        if any(
            isinstance(i.expr, P.WindowFuncCall) for i in stmt.items
        ):
            return None

        # -- partition (leaf scan tasks over vnode ranges) --------------
        if stmt.group_by:
            keys = [g.name for g in stmt.group_by]
            if not all(k in cols for k in keys):
                return None
            vn = _vnodes(cols, keys)
            if vn is None:
                return None
            part_of = vn % self.n_tasks
        else:
            # stateless scan/filter or scalar agg: round-robin ranges
            part_of = np.arange(n) % self.n_tasks

        # scalar aggregates need each task's surviving row count: a
        # WHERE can empty a partition, whose min/max placeholder (0)
        # must not contaminate the merge
        task_stmt = stmt
        if has_agg and not stmt.group_by:
            task_stmt = P.Select(
                items=stmt.items
                + (P.SelectItem(P.FuncCall("count", ("*",)), "__rows__"),),
                from_=stmt.from_,
                where=stmt.where,
                group_by=stmt.group_by,
                grouping_sets=stmt.grouping_sets,
            )

        partials: List[Dict[str, np.ndarray]] = []
        for t in range(self.n_tasks):
            sel = part_of == t
            task_cols = {k: v[sel] for k, v in cols.items()}
            # each task runs the same operator chain the local engine
            # uses (scan -> filter -> agg), over its partition only
            partials.append(
                self.engine._run_select_over(task_stmt, task_cols)
            )

        if stmt.group_by or not has_agg:
            # hash-partitioned groups are disjoint and plain scans
            # just append: concatenation IS the merge. Null lanes are
            # per-partition-conditional — union them, defaulting to
            # all-non-NULL where absent
            names = set().union(*partials)
            merged: Dict[str, np.ndarray] = {}
            for k in sorted(names):
                parts = []
                for p in partials:
                    if k in p:
                        parts.append(np.asarray(p[k]))
                    elif k.endswith("__null"):
                        base = p[k[: -len("__null")]]
                        parts.append(np.zeros(len(base), bool))
                    else:
                        return None  # ragged partial schema: fall back
                merged[k] = np.concatenate(parts)
            return merged

        # scalar aggregates: combine NON-EMPTY partials per the agg's
        # merge rule (two-phase agg)
        live = [p for p in partials if p["__rows__"][0] > 0]
        if not live:
            # preserve local-mode empty semantics exactly
            return self.engine._run_select_over(
                stmt, {k: v[:0] for k, v in cols.items()}
            )
        out: Dict[str, np.ndarray] = {}
        for i, item in enumerate(stmt.items):
            e = item.expr
            if not isinstance(e, P.FuncCall):
                return None  # mixed scalar select: fall back
            name = item.alias or f"{e.name}_{i}"
            # a partial flagged NULL (sum/min/max over zero surviving
            # rows) contributes nothing — merging its 0 fill value
            # would corrupt min/max/sum
            vals_list = [
                np.asarray(p[name])
                for p in live
                if not (
                    name + "__null" in p
                    and bool(np.asarray(p[name + "__null"])[0])
                )
            ]
            if not vals_list:
                out[name] = np.asarray([0])
                out[name + "__null"] = np.asarray([True])
                continue
            vals = np.concatenate(vals_list)
            if e.name in ("count", "sum"):
                out[name] = np.asarray([vals.sum()])
            elif e.name == "min":
                out[name] = np.asarray([vals.min()])
            else:
                out[name] = np.asarray([vals.max()])
        return out
