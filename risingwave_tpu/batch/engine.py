"""Local-mode batch engine: SELECT over MV snapshots.

Reference: the batch executor chain (src/batch/src/executor/: RowSeqScan
-> filter -> project -> agg -> order/limit) in local execution mode
(scheduler/local.rs:60). The scan source is a MaterializeExecutor
snapshot (the queryable MV) or a recovered storage table; filtering and
projection run through the same expression framework as streaming.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from risingwave_tpu.array.chunk import DataChunk
from risingwave_tpu.executors.materialize import MaterializeExecutor
from risingwave_tpu.sql import parser as P
from risingwave_tpu.sql.planner import (
    AGG_FUNCS,
    EXTENDED_AGGS,
    Binder,
    compile_scalar,
)


# aggregates the batch engine evaluates beyond the planner's kinds:
# DISTINCT counts (pandas nunique), string_agg / array_agg (the
# reference's ordered-set aggregates, impl/src/aggregate/string_agg.rs)
DISTINCT_AGG_NAMES = ("approx_count_distinct",)
COLLECT_AGGS = ("string_agg", "array_agg")


def _is_batch_agg(fc) -> bool:
    return isinstance(fc, P.FuncCall) and (
        fc.name in AGG_FUNCS
        or fc.name in EXTENDED_AGGS
        or fc.name in DISTINCT_AGG_NAMES
        or fc.name in COLLECT_AGGS
    )


def _and_join(conjuncts):
    out = None
    for c in conjuncts:
        out = c if out is None else P.BinaryOp("and", out, c)
    return out


def _strip_quals(ast, cols: set):
    """Rewrite qualified idents (a.x) to bare names for evaluation
    over a joined frame whose columns are disjoint across sides."""
    if isinstance(ast, P.Ident):
        if ast.name not in cols:
            raise KeyError(f"cannot resolve join column {ast}")
        return P.Ident(ast.name)
    if isinstance(ast, P.BinaryOp):
        return P.BinaryOp(
            ast.op, _strip_quals(ast.left, cols), _strip_quals(ast.right, cols)
        )
    if isinstance(ast, P.UnaryOp):
        return P.UnaryOp(ast.op, _strip_quals(ast.operand, cols))
    if isinstance(ast, P.FuncCall):
        return P.FuncCall(
            ast.name,
            tuple(
                _strip_quals(a, cols)
                if isinstance(a, (P.Ident, P.BinaryOp, P.UnaryOp, P.FuncCall))
                else a
                for a in ast.args
            ),
        )
    return ast  # literals etc. pass through


class BatchQueryEngine:
    """``tables`` maps name -> MaterializeExecutor (the MV catalog)."""

    spill_threshold_rows: "int | None" = None  # SET batch_spill_threshold
    last_spill_partitions = 0

    def __init__(self, tables: Dict[str, MaterializeExecutor]):
        self.tables = dict(tables)
        # distributed-mode task count, 0/1 = local mode; flipped like
        # the reference's QUERY_MODE session variable
        self.distributed_tasks = 0
        # session dictionary (set by SqlSession): string_agg decodes
        # VARCHAR codes, joins text, and encodes the result back
        self.strings = None
        # session catalog (set by SqlSession): array_agg decodes its
        # ELEMENTS by the arg column's logical type — the result edge
        # only decodes whole lanes, never values inside lists
        self.catalog = None

    def _elem_decoder(self, stmt, arg):
        """Per-element decode fn for collect aggregates."""
        if self.catalog is None or not isinstance(arg, P.Ident):
            return lambda v: v
        from risingwave_tpu.sql.typing import _env_of_rel
        from risingwave_tpu.types import DataType

        f = _env_of_rel(stmt.from_, self.catalog).get(arg.name)
        if f is None:
            return lambda v: v
        if f.dtype is DataType.VARCHAR and self.strings is not None:
            return lambda v: self.strings.decode_one(int(v))
        if f.dtype is DataType.DECIMAL:
            from decimal import Decimal

            sc = f.scale or 0
            return lambda v: Decimal(int(v)).scaleb(-sc)
        return lambda v: v

    def register(self, name: str, mview: MaterializeExecutor) -> None:
        self.tables[name] = mview

    def query(self, sql: str, stmt: "P.Select" = None) -> Dict[str, np.ndarray]:
        if stmt is None:
            stmt = P.parse(sql)
        if not isinstance(stmt, P.Select):
            raise ValueError("batch engine runs SELECT only")
        if self.distributed_tasks > 1:
            # distributed mode first; non-partitionable shapes fall
            # back to local (scheduler/local.rs:60 mode split)
            from risingwave_tpu.batch.distributed import (
                DistributedBatchRunner,
            )

            out = DistributedBatchRunner(
                self, self.distributed_tasks
            ).query(stmt)
            if out is not None:
                having = getattr(stmt, "having", None)
                if having is not None:
                    # merged rows are COMPLETE (two-phase agg finished):
                    # filtering here is correct for global aggregates
                    # and idempotent for grouped ones
                    out = self._having_filter(
                        having, {k: np.asarray(v) for k, v in out.items()}
                    )
                out = self._distinct(stmt, out)
                return out
        if isinstance(stmt.from_, P.Join):
            cols, alias = self._join_scan(stmt.from_), None
        elif isinstance(stmt.from_, P.TableRef):
            mv = self.tables[stmt.from_.name]
            cols, alias = mv.to_numpy(), stmt.from_.alias
        elif isinstance(stmt.from_, P.SubQuery):
            # derived table: run the inner select (its own WHERE/GROUP
            # BY/ORDER BY/LIMIT apply) and scan its result — NULL
            # companions fold into object lanes, the engine's nullable
            # column convention
            inner = self.query("", stmt=stmt.from_.select)
            cols = self._fold_null_lanes(inner)
            alias = stmt.from_.alias
        else:
            raise ValueError(
                "batch FROM must be an MV name, join, or subquery"
            )
        out = self._run_select_over(stmt, cols, alias)
        out = self._distinct(stmt, out)

        # OrderBy + Limit (src/batch/src/executor/{order_by,limit}.rs)
        out = self._order_limit(stmt, out)
        return out

    @staticmethod
    def _fold_null_lanes(out):
        """{v, v__null} pairs -> object lanes with None cells (the
        engine's nullable-column convention for scan inputs)."""
        cols = {}
        for k, v in out.items():
            if k.endswith("__null"):
                continue
            nl = out.get(k + "__null")
            arr = np.asarray(v)
            if nl is not None and np.asarray(nl).any():
                vals = arr.tolist()
                cols[k] = np.asarray(
                    [
                        None if m else x
                        for x, m in zip(vals, np.asarray(nl, bool))
                    ],
                    object,
                )
            else:
                cols[k] = arr
        return cols

    @staticmethod
    def _chunk_from_cols(cols, cap, nulls=None):
        """Snapshot columns -> DataChunk; object-dtype lanes (python-
        backend MVs embed None for SQL NULL) split into a numeric lane
        + null lane so expression eval stays NULL-strict. Callers with
        explicit null masks (e.g. agg ``__null`` companions) pass them
        via ``nulls`` and they merge with the derived ones."""
        data, nl_map = {}, {k: np.asarray(v, bool) for k, v in (nulls or {}).items()}
        for k, v in cols.items():
            a = np.asarray(v)
            if a.dtype == object:
                vals = a.tolist()
                nl = np.asarray([x is None for x in vals], bool)
                data[k] = np.asarray([0 if x is None else x for x in vals])
                if nl.any():
                    nl_map[k] = nl_map.get(k, False) | nl
            else:
                data[k] = a
        return DataChunk.from_numpy(data, cap, nulls=nl_map or None)

    def _run_select_over(self, stmt, cols, alias=None):
        """Filter -> agg/projection over one scan's columns (the task
        body shared by local mode and distributed partition tasks)."""
        n = len(next(iter(cols.values()))) if cols else 0

        # RowSeqScan -> chunk -> Filter via the shared expr framework
        schema = {k: v.dtype for k, v in cols.items()}
        binder = Binder(schema, alias)
        if n and stmt.where is not None:
            cap = max(1, 1 << (n - 1).bit_length())
            chunk = self._chunk_from_cols(cols, cap)
            keep_v, keep_n = compile_scalar(stmt.where, binder).eval(chunk)
            keep = np.asarray(keep_v).astype(bool)
            if keep_n is not None:
                keep &= ~np.asarray(keep_n)
            keep = keep[:n] & np.asarray(chunk.valid)[:n]
            cols = {k: v[keep] for k, v in cols.items()}
            n = int(keep.sum())

        # window functions (src/batch/src/executor/over_window.rs):
        # pandas per-partition transforms over the filtered scan
        if any(
            isinstance(it.expr, P.WindowFuncCall) for it in stmt.items
        ):
            if stmt.group_by:
                raise NotImplementedError(
                    "window functions over GROUP BY: aggregate in a "
                    "derived table first"
                )
            return self._over_window(stmt, cols, n, binder)

        # aggregation / projection
        if stmt.group_by:
            keys = [binder.resolve(g) for g in stmt.group_by]
            out = self._group_agg(stmt, cols, keys, binder)
            having = getattr(stmt, "having", None)
            if having is not None:
                out = self._having_filter(having, out)
        else:
            out = {}
            chunk_cache = [None]
            for i, item in enumerate(stmt.items):
                if _is_batch_agg(item.expr):
                    name = item.alias or f"{item.expr.name}_{i}"
                    vals, isnull = self._scalar_agg(
                        item.expr, cols, n, binder, stmt=stmt
                    )
                    out[name] = vals
                    if isnull:
                        out[name + "__null"] = np.array([True])
                else:
                    # unaliased names must match sql/typing's inference
                    # (the result edge keys decode on them)
                    if item.alias:
                        name = item.alias
                    elif isinstance(item.expr, P.Ident):
                        name = item.expr.name
                    elif isinstance(item.expr, P.FuncCall):
                        name = f"{item.expr.name}_{i}"
                    else:
                        name = f"col{i}"
                    vals, nl = self._eval_item(
                        item.expr, cols, n, binder, chunk_cache
                    )
                    out[name] = vals
                    if nl is not None and nl.any():
                        out[name + "__null"] = nl
            having = getattr(stmt, "having", None)
            if having is not None:
                # HAVING over a GLOBAL aggregate filters its single row
                out = self._having_filter(having, {
                    k: np.asarray(v) for k, v in out.items()
                })
        return out

    @staticmethod
    def _distinct(stmt, out):
        if not getattr(stmt, "distinct", False) or not out:
            return out
        import pandas as pd

        df = pd.DataFrame(out).drop_duplicates()
        return {k: df[k].to_numpy() for k in out}

    def _having_filter(self, having, out):
        """HAVING over the grouped OUTPUT columns (keys + agg aliases),
        evaluated through the shared expression framework."""
        value_cols = {
            k: v for k, v in out.items() if not k.endswith("__null")
        }
        # a NULL aggregate (min/sum over zero surviving rows) must make
        # the HAVING predicate NULL -> row dropped, not compare its
        # numeric fill value; carry the __null companions as masks
        null_masks = {
            k[: -len("__null")]: np.asarray(v, bool)
            for k, v in out.items()
            if k.endswith("__null") and k[: -len("__null")] in value_cols
        }
        n = len(next(iter(value_cols.values()))) if value_cols else 0
        if not n:
            return out
        hb = Binder(
            {k: np.asarray(v).dtype for k, v in value_cols.items()}, None
        )
        cap = max(1, 1 << (n - 1).bit_length())
        chunk = self._chunk_from_cols(value_cols, cap, nulls=null_masks or None)
        kv, kn = compile_scalar(having, hb).eval(chunk)
        keep = np.asarray(kv).astype(bool)[:n]
        if kn is not None:
            keep &= ~np.asarray(kn)[:n]
        return {k: np.asarray(v)[keep] for k, v in out.items()}

    def _order_limit(self, stmt, out):
        if stmt.order_by:
            lanes = []
            for ident, desc in reversed(stmt.order_by):
                if ident.name not in out:
                    raise ValueError(
                        f"ORDER BY column {ident.name!r} must appear "
                        "in the SELECT list (this engine sorts the "
                        "projected output)"
                    )
                lane = np.asarray(out[ident.name])
                nl = out.get(ident.name + "__null")
                if lane.dtype == object:
                    # None-embedded object lane (a folded subquery
                    # output): split into fill values + a null mask
                    vals = lane.tolist()
                    onl = np.asarray([x is None for x in vals], bool)
                    lane = np.asarray(
                        [0 if m else x for x, m in zip(vals, onl)]
                    )
                    nl = onl if nl is None else (np.asarray(nl, bool) | onl)
                lanes.append(-lane if desc else lane)
                if nl is not None:
                    # Postgres: NULL sorts as larger than every value —
                    # last under ASC, first under DESC; the null lane
                    # must dominate the fill value, so append it AFTER
                    # (lexsort: later keys are more significant)
                    nl = np.asarray(nl, bool)
                    lanes.append(~nl if desc else nl)
            order = np.lexsort(tuple(lanes))
            out = {k: v[order] for k, v in out.items()}
        if stmt.limit is not None:
            out = {k: v[: stmt.limit] for k, v in out.items()}
        return out

    def _over_window(self, stmt, cols, n, binder):
        """Batch OVER() (reference: src/batch/src/executor/
        over_window.rs): row_number/rank/dense_rank/lag/lead +
        sum/min/max/count over full partitions, plus trailing ROWS
        frames for the reducers. Output preserves scan row order."""
        import pandas as pd

        df = pd.DataFrame(cols)
        out: Dict[str, np.ndarray] = {}
        for i, item in enumerate(stmt.items):
            ast = item.expr
            if isinstance(ast, P.Ident):
                name = binder.resolve(ast)
                out[item.alias or name] = np.asarray(cols[name])
                continue
            if not isinstance(ast, P.WindowFuncCall):
                raise NotImplementedError(
                    "window SELECTs mix bare columns and OVER() calls "
                    "only (wrap expressions in a derived table)"
                )
            part = [binder.resolve(c) for c in ast.partition_by]
            if len(ast.order_by) > 1:
                raise NotImplementedError(
                    "OVER (... ORDER BY) supports one order column"
                )
            ocol = odesc = None
            if ast.order_by:
                oident, odesc = ast.order_by[0]
                ocol = binder.resolve(oident)
            order = df.sort_values(
                part + ([ocol] if ocol else []),
                ascending=[True] * len(part) + ([not odesc] if ocol else []),
                kind="stable",
            ) if (part or ocol) else df
            # count(*) and unpartitioned reducers work on a constant
            # lane: rows count as rows, never skipping NULL proxies
            order = order.assign(__one=1)
            # dropna=False: SQL puts NULL partition keys in their own
            # partition — pandas' default silently DROPS those rows
            gb = (
                order.groupby(part, sort=False, dropna=False)
                if part
                else None
            )
            fn, args = ast.func.name, ast.func.args
            if getattr(ast.func, "distinct", False):
                raise NotImplementedError(
                    f"{fn}(DISTINCT ...) OVER (...) unsupported"
                )
            name = item.alias or f"{fn}_{i}"
            nl = None
            if fn == "row_number":
                s = (gb.cumcount() if gb is not None else
                     pd.Series(np.arange(len(order)), index=order.index)) + 1
            elif fn in ("rank", "dense_rank"):
                if ocol is None:
                    raise ValueError(f"{fn}() needs ORDER BY")
                method = "min" if fn == "rank" else "dense"
                src = gb[ocol] if gb is not None else order[ocol]
                s = src.rank(method=method, ascending=not odesc)
            elif fn in ("lag", "lead"):
                col = binder.resolve(args[0])
                k = int(args[1].value) if len(args) > 1 else 1
                k = k if fn == "lag" else -k
                s = (gb[col].shift(k) if gb is not None
                     else order[col].shift(k))
                if len(args) > 2:
                    if not isinstance(args[2], P.Literal):
                        raise ValueError(
                            "lag/lead default must be a literal"
                        )
                    s = s.fillna(args[2].value)
                else:
                    nl = s.isna()
            elif fn in ("sum", "min", "max", "count"):
                if args == ("*",):
                    if fn != "count":
                        raise ValueError(f"{fn}(*) unsupported")
                    col = "__one"  # count ROWS, not non-NULL proxies
                    fn_eff = "sum"
                else:
                    col = binder.resolve(args[0])
                    fn_eff = fn
                if ast.frame is not None:
                    lo, hi = ast.frame
                    if hi != 0 or lo > 0:
                        raise NotImplementedError(
                            "batch ROWS frames support trailing "
                            "windows (N PRECEDING .. CURRENT ROW)"
                        )
                    window = -lo + 1
                    roll = (
                        gb[col] if gb is not None else order[col]
                    ).rolling(window, min_periods=1)
                    agg = {"count": "count"}.get(fn_eff, fn_eff)
                    s = getattr(roll, agg)()
                    if gb is not None:
                        s = s.reset_index(level=list(range(len(part))),
                                          drop=True)
                elif ocol is not None:
                    # SQL default frame with ORDER BY: RUNNING
                    # aggregate (RANGE UNBOUNDED PRECEDING .. CURRENT
                    # ROW) — computed as ROWS-cumulative, then ORDER-
                    # BY peers share the frame end (transform 'last')
                    src = gb[col] if gb is not None else order[col]
                    if fn_eff == "count":
                        s = src.transform(
                            lambda x: x.notna().cumsum()
                        ) if gb is not None else order[col].notna().cumsum()
                    else:
                        cum = {"sum": "cumsum", "min": "cummin",
                               "max": "cummax"}[fn_eff]
                        s = getattr(src, cum)()
                    peer_keys = [order[c] for c in part] + [order[ocol]]
                    s = s.groupby(peer_keys, dropna=False).transform(
                        "last"
                    )
                else:
                    s = (
                        gb[col].transform(fn_eff)
                        if gb is not None
                        else pd.Series(
                            getattr(order[col], fn_eff)(),
                            index=order.index,
                        )
                    )
            else:
                raise NotImplementedError(
                    f"window function {fn!r} unsupported in batch"
                )
            s = s.reindex(df.index).sort_index()
            vals = s.to_numpy()
            if nl is None and pd.isna(vals).any():
                nl = pd.Series(vals).isna()
            if nl is not None:
                nlv = np.asarray(nl.reindex(df.index).sort_index()
                                 if hasattr(nl, "reindex") else nl, bool)
                if nlv.any():
                    out[name + "__null"] = nlv
                    vals = np.asarray(
                        [0 if m else v for v, m in zip(vals.tolist(),
                                                       nlv.tolist())]
                    )
            if fn in (
                "row_number", "rank", "dense_rank", "count"
            ) and np.issubdtype(np.asarray(vals).dtype, np.floating):
                # pandas rank/rolling-count return float; these are
                # integral by definition
                a = np.asarray(vals, np.float64)
                vals = np.where(np.isnan(a), 0, a).astype(np.int64)
            out[name] = np.asarray(vals)
        return out

    @staticmethod
    def _join_quals(rel) -> set:
        """Every alias addressable inside a (possibly nested) join."""
        if isinstance(rel, P.Join):
            return BatchQueryEngine._join_quals(
                rel.left
            ) | BatchQueryEngine._join_quals(rel.right)
        return {rel.alias or rel.name}

    def _join_scan(self, join: P.Join) -> Dict[str, np.ndarray]:
        """Batch join over MV scans (reference: the batch
        HashJoinExecutor, src/batch/src/executor/join/), LEFT-DEEP
        multi-way: a nested left join evaluates recursively and its
        result becomes the probe side (the same tree shape the
        streaming planner lowers to). Column names must be disjoint
        across sides (alias/rename upstream); outer joins surface
        missing ints as NaN-capable float lanes."""
        import pandas as pd

        if isinstance(join.right, P.Join):
            raise ValueError(
                "batch joins are left-deep: nest on the left side"
            )

        def side(rel):
            if not isinstance(rel, P.TableRef):
                raise ValueError("batch join sides must be MV names")
            df = pd.DataFrame(self.tables[rel.name].to_numpy())
            # hidden planner lanes (_row_id) are not addressable in
            # batch SQL and would collide across sides
            df = df[[c for c in df.columns if not c.startswith("_")]]
            return rel.alias or rel.name, df

        if isinstance(join.left, P.Join):
            ldf = pd.DataFrame(self._join_scan(join.left))
            lquals = self._join_quals(join.left)
        else:
            lname, ldf = side(join.left)
            lquals = {lname}
        rname, rdf = side(join.right)
        overlap = set(ldf.columns) & set(rdf.columns)
        if overlap:
            raise ValueError(
                f"join sides share column names {overlap}; alias them apart"
            )

        pairs = []
        residual = []  # non-equi conjuncts -> NL/post-filter path

        def resolve(ident: P.Ident) -> str:
            if ident.qualifier in lquals and ident.name in ldf.columns:
                return ident.name
            if ident.qualifier == rname and ident.name in rdf.columns:
                return ident.name
            if ident.qualifier is None and (
                (ident.name in ldf.columns) != (ident.name in rdf.columns)
            ):
                return ident.name
            raise KeyError(f"cannot resolve join column {ident}")

        def walk(e):
            if isinstance(e, P.BinaryOp) and e.op == "and":
                walk(e.left)
                walk(e.right)
                return
            if (
                isinstance(e, P.BinaryOp)
                and e.op == "="
                and isinstance(e.left, P.Ident)
                and isinstance(e.right, P.Ident)
            ):
                try:
                    a, b = resolve(e.left), resolve(e.right)
                except KeyError:
                    residual.append(e)
                    return
                if a in ldf.columns and b in rdf.columns:
                    pairs.append((a, b))
                    return
                if b in ldf.columns and a in rdf.columns:
                    pairs.append((b, a))
                    return
                # same-side equality: an ordinary predicate
            residual.append(e)  # theta predicate: NL / post-filter

        walk(join.on)
        jt = join.join_type
        if not pairs:
            # NO equi keys: NESTED-LOOP join (reference: src/batch/src/
            # executor/join/nested_loop_join.rs) — cross product
            # filtered by the full ON predicate
            if jt not in ("inner", "left"):
                raise ValueError(
                    "non-equi batch joins support INNER/LEFT only"
                )
            return self._nl_join(ldf, rdf, join.on, jt)
        if residual and jt != "inner":
            raise ValueError(
                "equi + residual ON predicates support INNER joins "
                "only (outer-join padding happens before the residual)"
            )
        lk = [p[0] for p in pairs]
        rk = [p[1] for p in pairs]
        if jt in ("inner", "left", "right", "full"):
            how = {"full": "outer"}.get(jt, jt)
            m = ldf.merge(rdf, left_on=lk, right_on=rk, how=how)
        elif jt in ("left_semi", "left_anti"):
            hit = ldf.merge(
                rdf[rk].drop_duplicates(), left_on=lk, right_on=rk,
                how="left", indicator=True,
            )["_merge"] == "both"
            m = ldf[hit.values] if jt == "left_semi" else ldf[~hit.values]
        elif jt in ("right_semi", "right_anti"):
            hit = rdf.merge(
                ldf[lk].drop_duplicates(), left_on=rk, right_on=lk,
                how="left", indicator=True,
            )["_merge"] == "both"
            m = rdf[hit.values] if jt == "right_semi" else rdf[~hit.values]
        else:
            raise ValueError(f"unknown join type {jt!r}")
        out = {c: m[c].to_numpy() for c in m.columns if c != "_merge"}
        if residual:
            keep = self._eval_on(out, _and_join(residual))
            out = {k: v[keep] for k, v in out.items()}
        return out

    def _nl_join(self, ldf, rdf, on, jt):
        """Cross product + predicate filter; LEFT pads unmatched probe
        rows with NULLs (nested_loop_join.rs semantics). O(|L|*|R|) by
        nature — the optimizer should have picked equi keys if any."""
        import pandas as pd

        lx = ldf.assign(__x=1, __lid=np.arange(len(ldf)))
        rx = rdf.assign(__x=1)
        cross = lx.merge(rx, on="__x").drop(columns="__x")
        cols = {c: cross[c].to_numpy() for c in cross.columns}
        keep = (
            self._eval_on(cols, on)
            if len(cross)
            else np.zeros(0, bool)
        )
        inner = cross[keep]
        if jt == "left":
            matched = set(inner["__lid"].tolist())
            miss = lx[~lx["__lid"].isin(matched)].drop(columns="__x")
            pad = pd.DataFrame(
                {c: [None] * len(miss) for c in rdf.columns}
            )
            pad.index = miss.index
            inner = pd.concat(
                [inner, pd.concat([miss, pad], axis=1)],
                ignore_index=True,
            )
        return {
            c: inner[c].to_numpy()
            for c in inner.columns
            if c != "__lid"
        }

    def _eval_on(self, cols, on) -> np.ndarray:
        """Evaluate an ON predicate over joined columns: qualifiers
        strip to bare names (sides are disjoint by construction);
        NULL comparisons drop the row (SQL join semantics)."""
        n = len(next(iter(cols.values()))) if cols else 0
        if n == 0:
            return np.zeros(0, bool)
        stripped = _strip_quals(on, set(cols))
        cap = max(1, 1 << (n - 1).bit_length())
        # float NaN is this engine's outer-join NULL encoding: a NaN
        # cell must make the predicate NULL (drop), not compare as a
        # value (NaN != x is True in IEEE, NULL != x is NULL in SQL)
        nan_nulls = {}
        for k, v in cols.items():
            a = np.asarray(v)
            if np.issubdtype(a.dtype, np.floating) and np.isnan(a).any():
                nan_nulls[k] = np.isnan(a)
        chunk = self._chunk_from_cols(cols, cap, nulls=nan_nulls or None)
        binder = Binder(
            {k: np.asarray(v).dtype for k, v in cols.items()}, None
        )
        kv, kn = compile_scalar(stripped, binder).eval(chunk)
        keep = np.asarray(kv).astype(bool)[:n]
        if kn is not None:
            keep &= ~np.asarray(kn)[:n]
        return keep

    def _eval_item(self, ast, cols, n, binder, chunk_cache=None):
        """-> (values, null_lane | None): computed items keep their SQL
        NULLs (a UDF error row, NULL-strict arithmetic). ``chunk_cache``
        (a one-slot list) shares the converted DataChunk across a
        select's items — the object-lane None-scan is O(rows*cols)."""
        if isinstance(ast, P.Ident):
            return cols[binder.resolve(ast)], None
        cap = max(1, 1 << max(0, (n - 1)).bit_length()) if n else 1
        if chunk_cache is not None and chunk_cache[0] is not None:
            chunk = chunk_cache[0]
        else:
            chunk = self._chunk_from_cols(cols, cap)
            if chunk_cache is not None:
                chunk_cache[0] = chunk
        v, nl = compile_scalar(ast, binder).eval(chunk)
        return np.asarray(v)[:n], (
            np.asarray(nl)[:n] if nl is not None else None
        )

    def _scalar_agg(self, fc, cols, n, binder, stmt=None):
        """NULL-aware global aggregate: NULL cells (None in object
        lanes) are skipped; sum/min/max over zero surviving rows is SQL
        NULL — returned as (values, is_null) so the caller emits the
        ``__null`` companion; count(*) / count(col) never is."""
        if fc.args == ("*",):
            if fc.name != "count":
                raise ValueError(f"{fc.name}(*) unsupported")
            return np.array([n]), False
        x = np.asarray(cols[binder.resolve(fc.args[0])])
        if x.dtype == object:
            live = np.asarray([v for v in x.tolist() if v is not None])
        elif np.issubdtype(x.dtype, np.floating):
            live = x[~np.isnan(x)]  # outer joins surface NULL as NaN
        else:
            live = x
        if fc.name in DISTINCT_AGG_NAMES or getattr(fc, "distinct", False):
            if fc.name not in ("count",) + DISTINCT_AGG_NAMES:
                raise NotImplementedError(
                    f"{fc.name}(DISTINCT ...) unsupported"
                )
            return np.array([len(set(live.tolist()))]), False
        if fc.name in COLLECT_AGGS:
            if fc.name == "array_agg":
                if len(x) == 0:
                    return np.array([0]), True  # zero rows -> NULL
                # PG array_agg PRESERVES NULL elements
                edec = (
                    self._elem_decoder(stmt, fc.args[0])
                    if stmt is not None
                    else (lambda v: v)
                )
                arr = np.empty(1, object)
                arr[0] = [
                    None
                    if v is None or (isinstance(v, float) and np.isnan(v))
                    else edec(v)
                    for v in x.tolist()
                ]
                return arr, False
            if self.strings is None:
                raise ValueError("string_agg needs the session dictionary")
            if len(fc.args) < 2 or not isinstance(fc.args[1], P.Literal):
                raise ValueError(
                    "string_agg(col, 'sep') needs a literal separator"
                )
            if len(live) == 0:
                return np.array([0]), True  # all-NULL/empty -> NULL
            sep = str(fc.args[1].value)
            code = self.strings.encode_one(
                sep.join(self.strings.decode_one(int(c)) for c in live)
            )
            return np.array([code]), False
        if fc.name == "count":
            return np.array([len(live)]), False
        if len(live) == 0:
            return np.array([0]), True
        if fc.name in EXTENDED_AGGS:
            if fc.name in ("bool_and", "bool_or"):
                b = live.astype(bool)
                return np.array([b.all() if fc.name == "bool_and" else b.any()]), False
            f = live.astype(np.float64)
            if fc.name == "avg":
                return np.array([f.mean()]), False
            ddof = 0 if fc.name.endswith("_pop") else 1
            if len(f) <= ddof:
                return np.array([0.0]), True  # var_samp of 1 row = NULL
            var = f.var(ddof=ddof)
            if fc.name.startswith("stddev"):
                return np.array([np.sqrt(var)]), False
            return np.array([var]), False
        fn = {"sum": np.sum, "min": np.min, "max": np.max}[fc.name]
        return np.array([fn(live)]), False

    def _group_agg(self, stmt, cols, keys, binder):
        n = len(next(iter(cols.values()))) if cols else 0
        if (
            self.spill_threshold_rows is not None
            and n > self.spill_threshold_rows
        ):
            return self._group_agg_spilled(stmt, cols, keys, binder)
        return self._group_agg_mem(stmt, cols, keys, binder)

    def _group_agg_spilled(self, stmt, cols, keys, binder):
        """Spill-to-disk aggregation (reference: src/batch/src/spill/):
        hash-partition the input rows by group key into on-disk runs,
        aggregate one partition at a time (memory bounded by the
        largest partition, not the input), and concatenate — each key
        lives in exactly one partition, so results are exact."""
        import shutil
        import tempfile

        import pandas as pd

        P_PARTS = 8
        key_cols = list(keys)  # already resolved column names
        # vectorized partition hash — this branch exists FOR large n
        part = (
            pd.util.hash_pandas_object(
                pd.DataFrame({c: cols[c] for c in key_cols}), index=False
            ).to_numpy()
            % P_PARTS
        )
        # native numeric lanes save/load as-is (dtype-stable results);
        # only genuinely object lanes (None cells) stay boxed
        obj_cols = {k: np.asarray(v) for k, v in cols.items()}
        tmpdir = tempfile.mkdtemp(prefix="rw_batch_spill_")
        self.last_spill_partitions = 0
        try:
            paths = []
            for p in range(P_PARTS):
                m = part == p
                if not m.any():
                    continue
                path = f"{tmpdir}/part{p}.npz"
                np.savez(path, **{k: v[m] for k, v in obj_cols.items()})
                paths.append(path)
            self.last_spill_partitions = len(paths)
            outs = []
            for path in paths:
                z = np.load(path, allow_pickle=True)
                pcols = {k: z[k] for k in z.files}
                outs.append(
                    self._group_agg_mem(stmt, pcols, keys, binder)
                )
        finally:
            shutil.rmtree(tmpdir, ignore_errors=True)
        # concatenate partition results; a __null companion present in
        # ANY partition must exist for all (False-filled elsewhere)
        names = {nm for o in outs for nm in o}
        merged: Dict[str, np.ndarray] = {}
        for nm in names:
            parts = []
            for o in outs:
                if nm in o:
                    parts.append(np.asarray(o[nm]))
                elif nm.endswith("__null"):
                    base = nm[: -len("__null")]
                    parts.append(
                        np.zeros(len(o[base]), bool)
                    )
            merged[nm] = np.concatenate(parts)
        return merged

    def _group_agg_mem(self, stmt, cols, keys, binder):
        import pandas as pd

        df = pd.DataFrame(cols)
        # coerced-numeric companions for extended aggregates (object
        # lanes carry None cells; to_numeric makes them NaN, which every
        # pandas reducer skips — PG NULL-skipping semantics)
        for item in stmt.items:
            fc = item.expr
            if (
                isinstance(fc, P.FuncCall)
                and fc.name in EXTENDED_AGGS
                and fc.args != ("*",)
            ):
                col = binder.resolve(fc.args[0])
                if f"__num_{col}" not in df:
                    df[f"__num_{col}"] = pd.to_numeric(
                        df[col], errors="coerce"
                    )
        # dropna=False: SQL groups NULL keys (the _over_window path
        # passes the same flag for the same reason)
        gb = df.groupby(keys, sort=False, dropna=False)
        out: Dict[str, np.ndarray] = {}
        frames = {}
        src_cols: Dict[str, str] = {}
        ext_kinds: Dict[str, str] = {}
        for i, item in enumerate(stmt.items):
            if isinstance(item.expr, P.Ident):
                name = binder.resolve(item.expr)
                if name not in keys:
                    raise ValueError(f"{name!r} not in GROUP BY")
                continue
            fc = item.expr
            if not _is_batch_agg(fc):
                raise ValueError("items must be keys or aggregates")
            name = item.alias or f"{fc.name}_{i}"
            if fc.args == ("*",):
                if fc.name != "count":
                    raise ValueError(f"{fc.name}(*) unsupported")
                frames[name] = gb.size()
            elif fc.name in DISTINCT_AGG_NAMES or getattr(
                fc, "distinct", False
            ):
                if fc.name not in ("count",) + DISTINCT_AGG_NAMES:
                    raise NotImplementedError(
                        f"{fc.name}(DISTINCT ...) unsupported"
                    )
                col = binder.resolve(fc.args[0])
                frames[name] = gb[col].nunique()  # NULLs excluded
            elif fc.name in COLLECT_AGGS:
                col = binder.resolve(fc.args[0])
                if fc.name == "array_agg":
                    # PG array_agg PRESERVES NULL elements; VARCHAR/
                    # DECIMAL elements decode to SQL values (the edge
                    # never decodes inside lists)
                    import pandas as pd

                    edec = self._elem_decoder(stmt, fc.args[0])
                    frames[name] = gb[col].agg(
                        lambda x: [
                            None if pd.isna(v) else edec(v) for v in x
                        ]
                    )
                else:  # string_agg(col, sep); all-NULL group -> NULL
                    if self.strings is None:
                        raise ValueError(
                            "string_agg needs the session dictionary"
                        )
                    if len(fc.args) < 2 or not isinstance(
                        fc.args[1], P.Literal
                    ):
                        raise ValueError(
                            "string_agg(col, 'sep') needs a literal "
                            "separator"
                        )
                    sep = str(fc.args[1].value)
                    dec = self.strings.decode_one
                    enc = self.strings.encode_one

                    def _sagg(x, _sep=sep, _dec=dec, _enc=enc):
                        d = x.dropna()
                        if not len(d):
                            return np.nan
                        return _enc(_sep.join(_dec(int(c)) for c in d))

                    frames[name] = gb[col].agg(_sagg)
            elif fc.name in EXTENDED_AGGS:
                col = f"__num_{binder.resolve(fc.args[0])}"
                ext_kinds[name] = fc.name
                if fc.name == "avg":
                    frames[name] = gb[col].mean()
                elif fc.name == "bool_and":
                    frames[name] = gb[col].min()  # finished to bool below
                elif fc.name == "bool_or":
                    frames[name] = gb[col].max()
                else:  # var/stddev: NaN when n <= ddof (samp of 1 row)
                    ddof = 0 if fc.name.endswith("_pop") else 1
                    v = gb[col].var(ddof=ddof)
                    frames[name] = (
                        np.sqrt(v) if fc.name.startswith("stddev") else v
                    )
            elif fc.name == "sum":
                # min_count=1: sum over an all-NULL group is SQL NULL
                # (pandas' default min_count=0 would fabricate a 0)
                col = binder.resolve(fc.args[0])
                src_cols[name] = col
                frames[name] = gb[col].sum(min_count=1)
            else:
                col = binder.resolve(fc.args[0])
                src_cols[name] = col
                frames[name] = getattr(gb[col], {
                    "count": "count", "min": "min", "max": "max"
                }[fc.name])()
        if frames:
            res = pd.DataFrame(frames).reset_index()
        else:  # batch DISTINCT: GROUP BY with no aggregates
            res = df[keys].drop_duplicates()
        for item in stmt.items:
            if isinstance(item.expr, P.Ident):
                nm = binder.resolve(item.expr)
                import pandas as pd

                lane = res[nm]
                knl = pd.isna(lane).to_numpy()
                if knl.any():
                    # the NULL group's key surfaces as SQL NULL
                    out[item.alias or nm] = np.asarray(
                        [
                            0 if m else x
                            for x, m in zip(lane.tolist(), knl.tolist())
                        ]
                    )
                    out[(item.alias or nm) + "__null"] = knl
                else:
                    out[item.alias or nm] = lane.to_numpy()
        for name in frames:
            lane = res[name]
            nl = lane.isna().to_numpy()
            if nl.any():
                # NULL agg outputs (all-NULL group): numeric fill + the
                # __null companion the result edge / HAVING understand
                vals = lane.to_numpy()
                arr = np.asarray(
                    [0 if m else x for x, m in zip(vals.tolist(), nl.tolist())]
                )
                # pandas widens int sums to float64 once any group is
                # NaN — restore the integer domain unless the SOURCE
                # column is genuinely floating
                src = df[src_cols[name]] if name in src_cols else None
                int_like = src is not None and (
                    src.dtype == object
                    and all(
                        isinstance(v, (int, np.integer))
                        for v in src.dropna().tolist()
                    )
                    or np.issubdtype(src.dtype, np.integer)
                )
                if int_like and np.issubdtype(arr.dtype, np.floating):
                    arr = arr.astype(np.int64)
                out[name] = arr
                out[name + "__null"] = nl
            else:
                out[name] = lane.to_numpy()
        # finish bool aggregates: min/max over the 0/1 numeric lane
        for name, kind in ext_kinds.items():
            if kind in ("bool_and", "bool_or"):
                out[name] = (
                    np.asarray(out[name], dtype=np.float64) != 0
                )
        return out
