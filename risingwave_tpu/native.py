"""Native (C++) runtime components, loaded via ctypes.

Reference 2.10 note: the reference's whole runtime is native (Rust);
here the JAX/XLA compute plane stays Python-orchestrated, and the
host-side hot paths (MV row map; more to come) are C++ compiled
on first use into a cached shared library. Everything has a pure-
Python fallback, so a missing toolchain only costs speed.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC_DIR = os.path.join(os.path.dirname(__file__), "native_src")
_BUILD_DIR = os.path.join(_SRC_DIR, "_build")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    src = os.path.join(_SRC_DIR, "mv_map.cpp")
    try:
        # Rebuilds are gated on a source-content hash (not mtime): git
        # does not preserve mtimes, so a stale checked-out .so could
        # otherwise load silently after a clone (ADVICE r2, medium).
        import hashlib

        with open(src, "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:12]
        so = os.path.join(_BUILD_DIR, f"librw_native_{tag}.so")
        if not os.path.exists(so):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            tmp = so + ".tmp"
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", src, "-o", tmp],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, so)
            # only after the new build landed: drop artifacts of prior
            # source versions (a failed compile must not delete the
            # last working library)
            import glob

            for old in glob.glob(
                os.path.join(_BUILD_DIR, "librw_native*.so")
            ):
                if old != so:
                    try:
                        os.remove(old)
                    except OSError:
                        pass
        lib = ctypes.CDLL(so)
        lib.mv_new.restype = ctypes.c_void_p
        lib.mv_new.argtypes = [ctypes.c_int64, ctypes.c_int64]
        lib.mv_free.argtypes = [ctypes.c_void_p]
        lib.mv_apply.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.mv_len.restype = ctypes.c_int64
        lib.mv_len.argtypes = [ctypes.c_void_p]
        lib.mv_dump.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.mv_get.restype = ctypes.c_int32
        lib.mv_get.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        return lib
    except (OSError, subprocess.CalledProcessError):
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if not _TRIED:
            _LIB = _build_and_load()
            _TRIED = True
        return _LIB


class NativeMvMap:
    """int64-lane MV row map backed by the C++ unordered_map."""

    def __init__(self, k_arity: int, v_arity: int):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self.k_arity = k_arity
        self.v_arity = v_arity
        self._h = self._lib.mv_new(k_arity, v_arity)

    def __del__(self):
        if getattr(self, "_h", None) and self._lib is not None:
            self._lib.mv_free(self._h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.mv_len(self._h))

    def apply(self, keys: np.ndarray, vals: np.ndarray, is_del: np.ndarray):
        n = len(is_del)
        if n == 0:
            return
        keys = np.ascontiguousarray(keys, np.int64).reshape(n, self.k_arity)
        vals = (
            np.ascontiguousarray(vals, np.int64).reshape(n, self.v_arity)
            if self.v_arity
            else np.zeros((n, 0), np.int64)
        )
        is_del = np.ascontiguousarray(is_del, np.uint8)
        self._lib.mv_apply(
            self._h,
            keys.ctypes.data,
            vals.ctypes.data,
            is_del.ctypes.data,
            n,
        )

    def dump(self):
        n = len(self)
        keys = np.empty((n, self.k_arity), np.int64)
        vals = np.empty((n, self.v_arity), np.int64)
        if n:
            self._lib.mv_dump(self._h, keys.ctypes.data, vals.ctypes.data)
        return keys, vals

    def get(self, key) -> Optional[tuple]:
        k = np.asarray(key, np.int64)
        out = np.empty(self.v_arity, np.int64)
        if self._lib.mv_get(self._h, k.ctypes.data, out.ctypes.data):
            return tuple(out.tolist())
        return None
