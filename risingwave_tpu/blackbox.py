"""Black-box flight recorder + device-wedge sentinel.

The two failure modes that have destroyed whole TPU bench rounds leave
no evidence today: q7 *wedges the device* (BENCH_TPU_2/3: "device
wedged; stopping" after hanging until the 360s child alarm) and a lost
tunnel SIGKILLs the client mid-round (r04/r05: zero artifacts). Every
post-mortem so far was reconstructed from healthy-run data. This module
is the always-on answer — telemetry that survives the *process*, not
just the barrier:

- **Flight recorder** (``RECORDER``): a bounded in-memory ring of
  compact per-barrier records (epoch, per-stage ms from EpochTrace,
  dispatch/transfer counters from PROFILER, recompile hazards, channel
  depths, sampled device memory_stats, sentinel state), persisted
  incrementally to an append-only JSONL segment file with a bounded
  fsync cadence — a SIGKILL, OOM, or wedged device still leaves a
  readable black box on disk. ``python -m risingwave_tpu blackbox
  <path>`` reconstructs the last-N-barrier timeline and can emit a
  Perfetto-compatible trace via trace.render_chrome_trace.
- **Device-health sentinel** (``SENTINEL``): a daemon thread that
  issues a tiny jitted heartbeat op through a worker thread with a
  deadline and classifies the device ``ALIVE`` / ``SLOW`` / ``WEDGED``.
  On WEDGED it captures a forensic bundle (every thread's stack via
  ``sys._current_frames``, profiler counters + device forensics, a
  live-array census, the flight-recorder tail) to a durable
  ``WEDGE_*.json`` artifact and arms a structured :class:`DeviceWedged`
  that the runtime's barrier clock and ``GraphRuntime.wait_barrier``
  raise *instead of hanging* — recovery paths treat it like an actor
  fault (clear the wedge, abort the capture window, recover), not a
  process crash.

Hot-path contract (same as profiler.py): everything is gated on one
``enabled``/``running`` attribute check; recorder-on overhead is
budgeted <1% of a steady-state barrier (asserted in
tests/test_blackbox.py and enforced by ``perf_gate --blackbox``).

This module must stay importable without touching jax (the reader CLI
and the perf-gate reader smoke parse segments from plain processes):
jax is imported lazily inside the default heartbeat / forensics only.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from risingwave_tpu.metrics import REGISTRY

__all__ = [
    "RECORDER",
    "SENTINEL",
    "DeviceWedged",
    "FlightRecorder",
    "DeviceSentinel",
    "classify_latency",
    "from_env",
    "configure",
    "read_segment",
]

# sentinel device states (also the `device_state` gauge encoding)
ALIVE, SLOW, WEDGED, UNKNOWN = "ALIVE", "SLOW", "WEDGED", "UNKNOWN"
_STATE_GAUGE = {ALIVE: 0.0, SLOW: 1.0, WEDGED: 2.0, UNKNOWN: -1.0}


# parse-with-fallback env helper shared with the profiler (one copy)
from risingwave_tpu.profiler import _env_float


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def classify_latency(
    latency_ms: Optional[float], slow_ms: float, deadline_ms: float
) -> str:
    """Shared ALIVE/SLOW/WEDGED vocabulary: the in-process sentinel and
    the out-of-process tunnel prober (scripts/tpu_probe_monitor.py)
    classify with the same thresholds, so `device_state` events mean
    the same thing wherever they were observed. ``None`` latency means
    the probe never completed (deadline exceeded)."""
    if latency_ms is None or latency_ms >= deadline_ms:
        return WEDGED
    if latency_ms >= slow_ms:
        return SLOW
    return ALIVE


class DeviceWedged(RuntimeError):
    """The device stopped answering heartbeats within the watchdog
    deadline. Structured: carries the sentinel classification, the
    last heartbeat latency, and the forensic-bundle path — the runtime
    raises this at the barrier (and wait_barrier raises it mid-wait)
    instead of hanging until an outer alarm murders the process."""

    def __init__(
        self,
        msg: str,
        state: str = WEDGED,
        latency_ms: Optional[float] = None,
        bundle_path: str = "",
    ):
        super().__init__(msg)
        self.state = state
        self.latency_ms = latency_ms
        self.bundle_path = bundle_path


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class _Suppress:
    """Re-entrant thread-local suppression window (one tiny object per
    enter — no generator machinery on the barrier path)."""

    __slots__ = ("_tls",)

    def __init__(self, tls):
        self._tls = tls

    def __enter__(self):
        self._tls.suppress = getattr(self._tls, "suppress", 0) + 1
        return self

    def __exit__(self, *exc):
        self._tls.suppress -= 1
        return False


class FlightRecorder:
    """Bounded ring of per-barrier records + incremental append-only
    JSONL segment persistence. Record keys are compact (the segment is
    written on the barrier path); the reader expands them:

      k=h  header: pid, ts, ver, ring
      k=b  barrier: ts, ep(och), seq, ck(pt), wall(ms), st(ages_ms),
           bw (achieved_bw_frac), cb (chunk_bytes), sb (state_bytes),
           d (cumulative device dispatches), x ({d2h,h2d} cumulative),
           hz (cumulative recompile hazards), dep ({fragment: total
           input-channel depth}), sen (sentinel state), mem (sampled
           device memory_stats), mb (modeled bytes per barrier from the
           compiled-executable roofline), pf (padding-bytes fraction of
           the modeled traffic), tel ({fragment: fused telemetry-lane
           scalars: per-member rows + dirty groups})

    Counters are recorded CUMULATIVE (cheap snapshot, no per-record
    subtraction on the hot path); the reader derives per-barrier
    deltas. The ring is always available in memory (stall dumps and
    wedge bundles embed its tail); the segment file only exists when a
    directory is configured (RW_BLACKBOX_DIR / config [blackbox])."""

    SEGMENT_PREFIX = "BLACKBOX_"

    def __init__(self):
        self.enabled = True  # ring recording (in-memory, always cheap)
        self.ring: deque = deque(maxlen=256)
        self._lock = threading.Lock()
        self._tls = threading.local()  # pipeline-record suppression
        self.dir: Optional[str] = None  # None = no disk persistence
        self.fsync_interval_s = 2.0
        self.segment_max_bytes = 8_000_000
        self.mem_sample_every = 8  # device memory_stats cadence
        self._fh = None
        self._path: Optional[str] = None
        self._bytes = 0
        self._last_fsync = 0.0
        self._records = 0
        # distinguishes THIS recorder's headers from a previous
        # incarnation's in the same file (pid reuse appends): rotation
        # headers share the run id, a new process gets a fresh one
        self._run_id = f"{os.getpid()}-{int(time.time() * 1e3)}"

    # -- lifecycle --------------------------------------------------------
    def configure(
        self,
        dir: Optional[str] = None,
        ring: Optional[int] = None,
        fsync_interval_s: Optional[float] = None,
        segment_max_bytes: Optional[int] = None,
        enabled: Optional[bool] = None,
    ) -> "FlightRecorder":
        with self._lock:
            if enabled is not None:
                self.enabled = enabled
            if ring is not None and ring != self.ring.maxlen:
                self.ring = deque(self.ring, maxlen=max(8, int(ring)))
            if fsync_interval_s is not None:
                self.fsync_interval_s = max(0.0, fsync_interval_s)
            if segment_max_bytes is not None:
                self.segment_max_bytes = max(65_536, int(segment_max_bytes))
            if dir is not None and dir != self.dir:
                self._close_locked()
                self.dir = dir or None
        return self

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except OSError:
                pass
            try:
                self._fh.close()
            except OSError:
                pass
        self._fh = None
        self._path = None
        self._bytes = 0

    @property
    def segment_path(self) -> Optional[str]:
        return self._path

    # -- the hot-path hook ------------------------------------------------
    def record_barrier(self, trace, runtime=None) -> None:
        """One compact record per barrier. ``trace`` is an EpochTrace
        (duck-typed: epoch/seq/checkpoint/wall_ms/stages_ms/...).
        Never raises — the black box must not worsen the barrier."""
        if not self.enabled:
            return
        try:
            rec = self._build_record(trace, runtime)
        except Exception:  # noqa: BLE001 — forensic, never load-bearing
            return
        with self._lock:
            self._records += 1
            # device memory_stats is a PJRT call — sample, don't spam
            sample_mem = self._records % self.mem_sample_every == 1
        if sample_mem:
            mem = _device_memory_stats()
            if mem is not None:
                rec["mem"] = mem
        # publish ONLY once fully built: snapshot_tail hands out the
        # dicts by reference, so a concurrent stall dump / wedge bundle
        # must never see a record mutate mid-serialization
        with self._lock:
            self.ring.append(rec)
        REGISTRY.counter("blackbox_records_total").inc()
        if self.dir is not None:
            self._persist(rec)

    def _build_record(self, trace, runtime) -> Dict:
        from risingwave_tpu.profiler import PROFILER

        rec: Dict = {
            "k": "b",
            "ts": round(time.time(), 3),
            "ep": int(getattr(trace, "epoch", 0)),
            "seq": int(getattr(trace, "seq", 0)),
            "ck": bool(getattr(trace, "checkpoint", False)),
            "wall": round(float(getattr(trace, "wall_ms", 0.0)), 3),
            "st": {
                k: round(v, 3)
                for k, v in getattr(trace, "stages_ms", {}).items()
            },
            "bw": getattr(trace, "achieved_bw_frac", 0.0),
            "cb": int(getattr(trace, "chunk_bytes", 0)),
            "sb": int(getattr(trace, "state_bytes", 0)),
        }
        # cumulative counters: dispatches, transfers, recompile hazards
        # (reader derives per-barrier deltas)
        try:
            rec["d"] = int(PROFILER.total_dispatches())
            x = PROFILER.transfer_counts()
            if x.get("d2h") or x.get("h2d"):
                rec["x"] = {k: int(v) for k, v in x.items()}
        except Exception:
            pass
        hz = REGISTRY.counters.get("recompile_hazard_total")
        if hz is not None:
            total = hz.total()
            if total:
                rec["hz"] = int(total)
        # fused-engine tail (PR 11): an EpochTrace finalize() already
        # CONSUMED its barrier's deviceprof model (modeled bytes +
        # telemetry of the fragments that ran in it) — read it off the
        # trace; standalone pipeline barriers (no EpochTrace) consume
        # here instead. Either way a record only ever shows what THIS
        # barrier did — never a stale echo of an earlier one.
        mb = int(getattr(trace, "modeled_bytes", 0))
        pf = float(getattr(trace, "padding_bytes_frac", 0.0))
        tel = getattr(trace, "telemetry", None)
        if tel is None:
            try:
                from risingwave_tpu.deviceprof import DEVICEPROF

                tail = DEVICEPROF.consume_barrier()
                mb = mb or tail["modeled_bytes"]
                pf = pf or tail["padding_frac"]
                tel = tail["tel"]
            except Exception:
                tel = None
        if tel:
            rec["tel"] = tel
        if mb:
            rec["mb"] = mb
            rec["pf"] = pf
        # per-fragment channel depth (graph-backed fragments): the
        # wedge question "where is the data stuck" answered per barrier
        if runtime is not None:
            dep = {}
            for name, p in getattr(runtime, "fragments", {}).items():
                g = getattr(p, "graph", None)
                if g is None:
                    continue
                try:
                    dep[name] = int(
                        sum(
                            len(ch)
                            for a in g.actors
                            for _p, ch in a.inputs
                        )
                    )
                except Exception:
                    continue
            if dep:
                rec["dep"] = dep
        # freshness deltas as published (ISSUE 16): per-MV
        # commit->visible / source->visible / event-time-lag, compacted
        # to cv/sv/lag; plus the barrier's backpressure verdict
        fr = getattr(trace, "freshness", None)
        if fr:
            compact = {}
            for mv, ent in fr.items():
                row = {}
                for key, short in (
                    ("commit_to_visible_ms", "cv"),
                    ("source_to_visible_ms", "sv"),
                    ("event_time_lag_ms", "lag"),
                ):
                    v = ent.get(key)
                    if v is not None:
                        row[short] = round(float(v), 3)
                if row:
                    compact[mv] = row
            if compact:
                rec["fr"] = compact
        bpf = getattr(trace, "backpressure_fragment", None)
        if bpf:
            rec["bp"] = {
                "f": bpf,
                "ms": round(
                    float(getattr(trace, "backpressure_ms", 0.0)), 3
                ),
            }
        # mesh observability (ISSUE 18): compact per-shard attribution
        # for sharded barriers — shard count, coverage, phase split,
        # per-shard local ms, (src,dst) row matrix, skew verdict
        msh = getattr(trace, "mesh", None)
        if msh:
            try:
                rec["msh"] = {
                    "n": msh.get("n_shards"),
                    "wall": round(float(msh.get("wall_ms", 0.0)), 3),
                    "att": round(
                        float(msh.get("attributed_ms", 0.0)), 3
                    ),
                    "cov": round(
                        float(msh.get("coverage_frac", 0.0)), 4
                    ),
                    "ph": {
                        k: round(float(v), 3)
                        for k, v in (msh.get("phases_ms") or {}).items()
                        if v
                    },
                    "loc": [
                        round(float(v), 3)
                        for v in (msh.get("shard_local_ms") or [])
                    ],
                    "xm": msh.get("exchange", {}).get("rows"),
                    "skew": msh.get("skew"),
                }
            except Exception:  # noqa: BLE001 — recorder never faults
                pass
        sen = SENTINEL
        if sen.running or sen.state != UNKNOWN:
            rec["sen"] = sen.state
        return rec

    def suppress_pipeline_records(self) -> "_Suppress":
        """Context for drivers that record their own barrier-level
        records (the StreamingRuntime's EpochTrace path, recovery
        replay): fragment-level Pipeline.barrier calls inside it stay
        silent — one barrier, one record, monotonic epochs."""
        return _Suppress(self._tls)

    def record_pipeline_barrier(
        self, epoch: int, dispatch_ms: float, device_ms: float
    ) -> None:
        """Standalone Pipeline/TwoInputPipeline barriers (the bench q7/
        q8 drivers) ride the same black box without an EpochTrace."""
        if not self.enabled or getattr(self._tls, "suppress", 0):
            return
        from types import SimpleNamespace

        self.record_barrier(
            SimpleNamespace(
                epoch=epoch,
                seq=0,
                checkpoint=False,
                wall_ms=dispatch_ms + device_ms,
                stages_ms={
                    "dispatch": dispatch_ms,
                    "device_step": device_ms,
                },
            )
        )

    # -- persistence ------------------------------------------------------
    def _persist(self, rec: Dict) -> None:
        line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
        with self._lock:
            try:
                if self._fh is None:
                    self._open_locked()
                if self._bytes + len(line) > self.segment_max_bytes:
                    self._rotate_locked()
                self._fh.write(line)
                self._fh.flush()  # survive SIGKILL up to the OS cache
                self._bytes += len(line)
                now = time.monotonic()
                if now - self._last_fsync >= self.fsync_interval_s:
                    t0 = time.perf_counter()
                    os.fsync(self._fh.fileno())
                    REGISTRY.histogram("blackbox_fsync_ms").observe(
                        (time.perf_counter() - t0) * 1e3
                    )
                    REGISTRY.counter("blackbox_fsyncs_total").inc()
                    self._last_fsync = now
            except (OSError, ValueError):
                # unwritable dir / disk full / malformed path: the ring
                # keeps recording; drop persistence, not the barrier
                self._close_locked()
                self.dir = None
                REGISTRY.counter("blackbox_persist_errors_total").inc()

    def _open_locked(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        self._path = os.path.join(
            self.dir, f"{self.SEGMENT_PREFIX}{os.getpid()}.jsonl"
        )
        self._fh = open(self._path, "a")
        self._bytes = 0
        try:
            self._bytes = os.fstat(self._fh.fileno()).st_size
        except OSError:
            pass
        hdr = {
            "k": "h",
            "pid": os.getpid(),
            "run": self._run_id,
            "ts": round(time.time(), 3),
            "ver": 1,
            "ring": self.ring.maxlen,
        }
        line = json.dumps(hdr, separators=(",", ":")) + "\n"
        self._fh.write(line)
        self._fh.flush()
        self._bytes += len(line)
        self._last_fsync = time.monotonic()

    def _rotate_locked(self) -> None:
        """Bounded disk: the current segment becomes ``<path>.old``
        (replacing any previous rotation) and a fresh segment opens —
        the reader merges both, so the readable window is at least
        ``segment_max_bytes`` of history."""
        path = self._path
        self._close_locked()
        try:
            os.replace(path, path + ".old")
        except OSError:
            pass
        self._open_locked()
        REGISTRY.counter("blackbox_rotations_total").inc()

    # -- read surfaces ----------------------------------------------------
    def snapshot_tail(self, n: int = 32) -> List[Dict]:
        with self._lock:
            return list(self.ring)[-n:]

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "records": self._records,
                "ring_len": len(self.ring),
                "segment": self._path,
                "dir": self.dir,
            }


def _device_memory_stats() -> Optional[Dict]:
    """Sampled device HBM stats (None on CPU / failure). Lazy jax
    import — reader-only processes never pay it."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return None
        # keep the load-bearing subset (full stats are verbose)
        keep = (
            "bytes_in_use",
            "peak_bytes_in_use",
            "bytes_limit",
            "largest_free_block_bytes",
            "num_allocs",
        )
        return {k: int(stats[k]) for k in keep if k in stats}
    except Exception:
        return None


# ---------------------------------------------------------------------------
# device-health sentinel
# ---------------------------------------------------------------------------

_HB_LOCK = threading.Lock()
_HB_FN = None
_HB_ARG = None


def _default_heartbeat() -> None:
    """The tiny jitted heartbeat op: one dispatch + one block. If the
    device queue is wedged this blocks — which is exactly the signal
    (the worker thread absorbs the block; the monitor times it out)."""
    global _HB_FN, _HB_ARG
    import jax

    with _HB_LOCK:
        if _HB_FN is None:
            import jax.numpy as jnp

            _HB_FN = jax.jit(lambda x: (x + 1).sum())
            _HB_ARG = jnp.zeros(8, jnp.int32)
    jax.block_until_ready(_HB_FN(_HB_ARG))


class DeviceSentinel:
    """Heartbeat-based device-wedge detector.

    Two threads: ``rw-sentinel`` (monitor — never touches the device)
    requests a beat every ``interval_s`` from ``rw-sentinel-beat`` (the
    worker that actually dispatches the heartbeat op) and waits at most
    ``deadline_s``. A worker stuck inside a device call cannot be
    interrupted from Python, so the monitor classifies WEDGED by
    timeout, captures the forensic bundle while the device evidence is
    still live, arms :class:`DeviceWedged`, and keeps watching: if the
    stuck beat eventually completes (tunnel revived), the state heals
    to ALIVE on the next cycle. While a beat is stuck no new worker is
    spawned — at most the one extra (stuck) thread ever exists.

    ``check()`` is the runtime hook: one attribute read when healthy,
    raises the armed DeviceWedged when not. Recovery calls
    ``clear_wedge()`` (treat-like-an-actor-fault contract: recover,
    don't crash) and ``abort_capture()`` closes an in-flight bundle
    window the way PROFILER.abort_captures closes profile windows."""

    def __init__(self):
        self.interval_s = 5.0
        self.slow_ms = 1000.0
        self.deadline_s = 20.0
        self.dir: Optional[str] = None  # default: RECORDER.dir / RW_STALL_DIR
        self.state_file: Optional[str] = None  # heartbeat status JSON
        self.heartbeat_fn: Callable[[], None] = _default_heartbeat
        self.on_wedge: Optional[Callable[[DeviceWedged], None]] = None
        self.state = UNKNOWN
        self.last_latency_ms: Optional[float] = None
        self.beats = 0
        self.wedges = 0
        self.running = False
        self._wedged: Optional[DeviceWedged] = None
        self._capture_open = False  # orphan-audit surface
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._worker: Optional[threading.Thread] = None
        self._beat_req = threading.Event()
        self._beat_done = threading.Event()
        self._beat_err: Optional[BaseException] = None
        self._bundle_seq = 0

    # -- lifecycle --------------------------------------------------------
    def start(
        self,
        interval_s: Optional[float] = None,
        slow_ms: Optional[float] = None,
        deadline_s: Optional[float] = None,
        heartbeat_fn: Optional[Callable[[], None]] = None,
        on_wedge: Optional[Callable[[DeviceWedged], None]] = None,
        dir: Optional[str] = None,
    ) -> "DeviceSentinel":
        with self._lock:
            if interval_s is not None:
                self.interval_s = max(0.01, interval_s)
            if slow_ms is not None:
                self.slow_ms = slow_ms
            if deadline_s is not None:
                self.deadline_s = max(0.05, deadline_s)
            if heartbeat_fn is not None:
                self.heartbeat_fn = heartbeat_fn
            if on_wedge is not None:
                self.on_wedge = on_wedge
            if dir is not None:
                self.dir = dir
            if self.running:
                return self
            self.running = True
            self._stop.clear()
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True, name="rw-sentinel"
            )
            self._monitor.start()
        return self

    def stop(self, join_timeout: float = 5.0) -> None:
        with self._lock:
            if not self.running:
                return
            self.running = False
            self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=join_timeout)
        # the worker exits on the stop flag unless stuck in the device
        # call itself (daemon thread; nothing can unstick it from here)
        w = self._worker
        if w is not None:
            w.join(timeout=join_timeout)
            if not w.is_alive():
                self._worker = None

    # -- runtime hooks ----------------------------------------------------
    def check(self) -> None:
        """Raise the armed DeviceWedged (the barrier-clock hook). One
        attribute read when healthy."""
        w = self._wedged
        if w is not None:
            raise w

    def wedged_error(self) -> Optional[DeviceWedged]:
        return self._wedged

    def clear_wedge(self) -> None:
        """Recovery treats a wedge like an actor fault: clear the armed
        error so the recovered runtime's next barrier proceeds; a still-
        wedged device re-arms on the next missed heartbeat."""
        self._wedged = None

    def abort_capture(self) -> int:
        """Close an in-flight wedge-capture window (recovery hygiene,
        the PROFILER.abort_captures analogue). Returns 1 if a window
        was open."""
        with self._lock:
            was = self._capture_open
            self._capture_open = False
        return int(was)

    def snapshot(self) -> Dict:
        return {
            "running": self.running,
            "state": self.state,
            "last_latency_ms": self.last_latency_ms,
            "beats": self.beats,
            "wedges": self.wedges,
            "wedged": repr(self._wedged) if self._wedged else None,
            "interval_s": self.interval_s,
            "deadline_s": self.deadline_s,
        }

    # -- internals --------------------------------------------------------
    def _ensure_worker(self) -> bool:
        """True iff a worker is available for a new beat. A worker
        still stuck in a previous beat means the device is still
        blocked — don't pile up threads, stay WEDGED."""
        w = self._worker
        if w is not None and w.is_alive():
            return not self._beat_req.is_set()
        self._beat_req.clear()
        self._beat_done.clear()
        self._worker = threading.Thread(
            target=self._worker_loop, daemon=True, name="rw-sentinel-beat"
        )
        self._worker.start()
        return True

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            if not self._beat_req.wait(timeout=0.2):
                continue
            self._beat_req.clear()
            try:
                self.heartbeat_fn()
                self._beat_err = None
            except BaseException as e:  # noqa: BLE001 — classified below
                self._beat_err = e
            self._beat_done.set()

    def _monitor_loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self._beat_once()
            except Exception:  # noqa: BLE001 — the watchdog never dies
                pass

    def _beat_once(self) -> None:
        if not self._ensure_worker():
            # previous beat still stuck inside the device call: the
            # wedge persists — keep the state + armed error current
            self._transition(WEDGED, None)
            return
        self._beat_done.clear()
        t0 = time.perf_counter()
        self._beat_req.set()
        done = self._beat_done.wait(timeout=self.deadline_s)
        latency_ms = (time.perf_counter() - t0) * 1e3
        self.beats += 1
        if not done:
            self._transition(WEDGED, None)
            return
        if self._beat_err is not None:
            # a raising heartbeat (device runtime error) is as wedged
            # as a silent one, but carries a cause worth keeping
            self._transition(WEDGED, latency_ms, err=self._beat_err)
            return
        self.last_latency_ms = latency_ms
        self._transition(
            classify_latency(latency_ms, self.slow_ms, self.deadline_s * 1e3),
            latency_ms,
        )

    def _transition(
        self,
        new_state: str,
        latency_ms: Optional[float],
        err: Optional[BaseException] = None,
    ) -> None:
        prev = self.state
        self.state = new_state
        REGISTRY.counter("sentinel_heartbeats_total").inc(state=new_state)
        REGISTRY.gauge("device_state").set(_STATE_GAUGE[new_state])
        if new_state != prev:
            try:
                from risingwave_tpu.event_log import EVENT_LOG

                EVENT_LOG.record(
                    "device_state",
                    state=new_state,
                    prev=prev,
                    latency_ms=(
                        round(latency_ms, 1) if latency_ms is not None else None
                    ),
                    source="sentinel",
                )
            except Exception:
                pass
        if new_state == WEDGED:
            if self._wedged is None:
                # first detection of THIS wedge: ARM FIRST, capture
                # after — the forensic bundle touches the (wedged)
                # device and may itself block, and the whole point is
                # that check()/wait_barrier/on_wedge fail fast instead
                # of sitting out an outer alarm
                self.wedges += 1
                wedged = DeviceWedged(
                    "device wedged: heartbeat exceeded "
                    f"{self.deadline_s}s deadline"
                    + (f" ({err!r})" if err is not None else ""),
                    latency_ms=latency_ms,
                )
                self._wedged = wedged
                cb = self.on_wedge
                if cb is not None:
                    try:
                        cb(wedged)
                    except Exception:
                        pass
                wedged.bundle_path = self._capture_wedge_bundle(
                    latency_ms, err
                )
        else:
            # ANY completed heartbeat disarms: the device answers
            # (ALIVE, or SLOW — a congested tunnel is usable), so a
            # stale armed wedge must not keep failing barriers
            self._wedged = None
        # written LAST so the file reflects the wedge counter/bundle
        # the transition just produced
        self._write_state_file(latency_ms)

    def _write_state_file(self, latency_ms: Optional[float]) -> None:
        """One-line status JSON, atomically replaced every beat — the
        surface bench_on_healthy tails into BENCH_WATCH.log."""
        path = self.state_file
        if path is None:
            d = self.dir or RECORDER.dir
            if d is None:
                return
            path = os.path.join(d, "SENTINEL_STATE.json")
        doc = {
            "ts": round(time.time(), 3),
            "state": self.state,
            "latency_ms": (
                round(latency_ms, 1) if latency_ms is not None else None
            ),
            "beats": self.beats,
            "wedges": self.wedges,
            "pid": os.getpid(),
        }
        try:
            # overload ladder rung (the memory governor's gauge), so
            # bench_on_healthy can tail THROTTLED/SHEDDING windows into
            # BENCH_WATCH.log alongside the device heartbeat
            from risingwave_tpu.metrics import REGISTRY
            from risingwave_tpu.runtime.memory_governor import LADDER

            g = REGISTRY.gauges.get("overload_state")
            if g is not None:
                i = int(g.get())
                doc["overload_state"] = (
                    LADDER[i] if 0 <= i < len(LADDER) else str(i)
                )
        except Exception:  # noqa: BLE001 — status stays heartbeat-only
            pass
        try:
            # mesh skew + exchange pressure (ISSUE 18): sharded runs
            # surface the hot-shard fraction and cumulative exchange
            # rows so bench_on_healthy can tail skew transitions
            from risingwave_tpu.metrics import REGISTRY as _REG
            from risingwave_tpu.parallel.meshprof import MESHPROF

            if MESHPROF.enabled:
                g = _REG.gauges.get("shard_skew_frac")
                if g is not None:
                    doc["shard_skew_frac"] = round(float(g.get()), 4)
                g = _REG.gauges.get("mesh_coverage_frac")
                if g is not None:
                    doc["mesh_coverage_frac"] = round(float(g.get()), 4)
                snap = MESHPROF.table_snapshot()
                ex = snap.get("exchange") or {}
                if ex.get("rows"):
                    doc["exchange_rows_total"] = int(
                        sum(sum(r) for r in ex["rows"])
                    )
        except Exception:  # noqa: BLE001 — status stays heartbeat-only
            pass
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            pass

    def _capture_wedge_bundle(
        self, latency_ms: Optional[float], err: Optional[BaseException]
    ) -> str:
        """The forensic bundle a wedge leaves behind: thread stacks,
        device forensics, profiler counters, the flight-recorder tail,
        recent events. Durable WEDGE_*.json (tempdir fallback). Never
        raises."""
        import sys
        import traceback

        with self._lock:
            self._capture_open = True
            self._bundle_seq += 1
            seq = self._bundle_seq
        doc: Dict = {
            "reason": (
                f"heartbeat exceeded {self.deadline_s}s deadline"
                if latency_ms is None
                else f"heartbeat classified WEDGED at {latency_ms:.1f}ms"
            ),
            "ts": time.time(),
            "pid": os.getpid(),
            "state": self.state,
            "last_latency_ms": self.last_latency_ms,
            "beats": self.beats,
            "heartbeat_error": repr(err) if err is not None else None,
        }
        try:
            names = {t.ident: t.name for t in threading.enumerate()}
            doc["threads"] = {
                f"{names.get(tid, '?')}({tid})": traceback.format_stack(frame)
                for tid, frame in sys._current_frames().items()
            }
        except Exception as e:
            doc["threads"] = repr(e)
        try:
            from risingwave_tpu.profiler import PROFILER, device_forensics

            doc["device"] = device_forensics()
            doc["profiler"] = PROFILER.snapshot()
        except Exception as e:
            doc["device"] = repr(e)
        doc["recorder_tail"] = RECORDER.snapshot_tail(64)
        try:
            from risingwave_tpu.event_log import EVENT_LOG

            doc["recent_events"] = EVENT_LOG.events(limit=20)
        except Exception:
            pass
        d = self.dir or RECORDER.dir or os.environ.get("RW_STALL_DIR", ".")
        path = os.path.join(d, f"WEDGE_{int(time.time())}_{seq}.json")
        try:
            # broad except + finally: the never-raises contract must
            # hold against serialization failures too (not just
            # OSError), and the capture window must ALWAYS close — a
            # leaked window would trip the orphan audits forever
            try:
                with open(path, "w") as f:
                    json.dump(doc, f, indent=1, default=str)
            except Exception:  # noqa: BLE001
                import tempfile

                path = os.path.join(
                    tempfile.gettempdir(), os.path.basename(path)
                )
                try:
                    with open(path, "w") as f:
                        json.dump(doc, f, indent=1, default=str)
                except Exception:  # noqa: BLE001
                    path = ""
        finally:
            with self._lock:
                self._capture_open = False
        REGISTRY.counter("wedge_dumps_total").inc()
        try:
            from risingwave_tpu.event_log import EVENT_LOG

            EVENT_LOG.record("wedge_dump", path=path, state=self.state)
        except Exception:
            pass
        return path


# ---------------------------------------------------------------------------
# process singletons + config/env plumbing
# ---------------------------------------------------------------------------

RECORDER = FlightRecorder()
SENTINEL = DeviceSentinel()


def from_env() -> None:
    """Honor RW_BLACKBOX_* on the process singletons (the operator's
    no-restart escape hatch; env wins over the [blackbox] config
    section, same precedence as RW_PROFILE/RW_RETRY). No-op when
    nothing is set — runtimes call this on every construction path."""
    raw = os.environ.get("RW_BLACKBOX")
    if raw is not None and raw.strip().lower() in ("0", "off", "false"):
        RECORDER.configure(enabled=False)
    elif raw is not None:
        RECORDER.configure(enabled=True)
    d = os.environ.get("RW_BLACKBOX_DIR")
    if d:
        RECORDER.configure(
            dir=d,
            ring=_env_int("RW_BLACKBOX_RING", RECORDER.ring.maxlen),
            fsync_interval_s=_env_float(
                "RW_BLACKBOX_FSYNC_S", RECORDER.fsync_interval_s
            ),
            segment_max_bytes=_env_int(
                "RW_BLACKBOX_SEGMENT_MAX", RECORDER.segment_max_bytes
            ),
        )
    if os.environ.get("RW_BLACKBOX_SENTINEL") == "1" and not SENTINEL.running:
        SENTINEL.start(
            interval_s=_env_float(
                "RW_BLACKBOX_HEARTBEAT_S", SENTINEL.interval_s
            ),
            slow_ms=_env_float("RW_BLACKBOX_SLOW_MS", SENTINEL.slow_ms),
            deadline_s=_env_float(
                "RW_BLACKBOX_DEADLINE_S", SENTINEL.deadline_s
            ),
            dir=d or None,
        )


def configure(cfg) -> None:
    """Apply a config.BlackboxConfig ([blackbox] TOML section); env
    knobs win afterwards."""
    RECORDER.configure(
        enabled=getattr(cfg, "enabled", True),
        dir=getattr(cfg, "dir", "") or None,
        ring=getattr(cfg, "ring_barriers", None),
        fsync_interval_s=getattr(cfg, "fsync_interval_s", None),
        segment_max_bytes=getattr(cfg, "segment_max_bytes", None),
    )
    if getattr(cfg, "sentinel", False) and not SENTINEL.running:
        SENTINEL.start(
            interval_s=getattr(cfg, "sentinel_interval_s", None),
            slow_ms=getattr(cfg, "sentinel_slow_ms", None),
            deadline_s=getattr(cfg, "sentinel_deadline_s", None),
            dir=getattr(cfg, "dir", "") or None,
        )
    from_env()


# ---------------------------------------------------------------------------
# segment reader (the CLI's engine; no jax required)
# ---------------------------------------------------------------------------


def read_segment(path: str, last: Optional[int] = None) -> Dict:
    """Parse a black-box segment (file, or a directory holding
    ``BLACKBOX_*.jsonl``). Tolerates a torn final line (SIGKILL mid-
    write) and merges a rotated ``.old`` sibling. Returns::

        {"header": {...} | None, "records": [expanded...],
         "torn_lines": N, "monotonic": bool, "source": [paths...]}

    Records are expanded to long keys with per-barrier counter deltas
    derived from the cumulative fields."""
    paths: List[str] = []
    if os.path.isdir(path):
        segs = sorted(
            f
            for f in os.listdir(path)
            if f.startswith(FlightRecorder.SEGMENT_PREFIX)
            and f.endswith(".jsonl")
        )
        if not segs:
            raise FileNotFoundError(f"no BLACKBOX_*.jsonl under {path!r}")
        newest = max(
            segs, key=lambda f: os.path.getmtime(os.path.join(path, f))
        )
        path = os.path.join(path, newest)
    if os.path.exists(path + ".old"):
        paths.append(path + ".old")
    paths.append(path)
    header = None
    raw: List[Dict] = []  # barrier records + inline header markers
    torn = 0
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    torn += 1  # torn tail (SIGKILL mid-write): expected
                    continue
                if rec.get("k") in ("h", "b"):
                    raw.append(rec)
    records: List[Dict] = []
    prev_d = prev_hz = None
    prev_x: Optional[Dict] = None
    run_start = False  # first barrier after a NEW run's header
    last_run = None
    monotonic = True
    for rec in raw:
        if rec.get("k") == "h":
            # a header from a DIFFERENT run id is a run boundary
            # (append-mode segment + pid reuse stacks two runs in one
            # file): the new run's epochs restart and its cumulative
            # counters reset — neither is a broken timeline. A header
            # with the SAME run id is just a rotation inside one run:
            # deltas and monotonicity continue across it. Headers
            # without a run id (old segments) conservatively reset.
            new_run = rec.get("run") is None or rec.get("run") != last_run
            last_run = rec.get("run")
            header = rec
            if new_run:
                prev_d = prev_hz = None
                prev_x = None
                run_start = True
            continue
        out = {
            "ts": rec.get("ts"),
            "epoch": rec.get("ep"),
            "seq": rec.get("seq"),
            "checkpoint": rec.get("ck"),
            "wall_ms": rec.get("wall"),
            "stages_ms": rec.get("st", {}),
            "achieved_bw_frac": rec.get("bw"),
            "chunk_bytes": rec.get("cb"),
            "state_bytes": rec.get("sb"),
            "sentinel": rec.get("sen"),
        }
        if "dep" in rec:
            out["channel_depths"] = rec["dep"]
        if "fr" in rec:
            out["freshness"] = rec["fr"]
        if "bp" in rec:
            out["backpressure"] = rec["bp"]
        if "msh" in rec:
            m = rec["msh"]
            out["mesh"] = {
                "n_shards": m.get("n"),
                "wall_ms": m.get("wall"),
                "attributed_ms": m.get("att"),
                "coverage_frac": m.get("cov"),
                "phases_ms": m.get("ph", {}),
                "shard_local_ms": m.get("loc", []),
                "exchange_rows": m.get("xm"),
                "skew": m.get("skew"),
            }
        if "mem" in rec:
            out["memory_stats"] = rec["mem"]
        if "mb" in rec:
            out["modeled_bytes"] = rec["mb"]
            out["padding_bytes_frac"] = rec.get("pf", 0.0)
        if "tel" in rec:
            out["telemetry"] = rec["tel"]
        if "d" in rec:
            out["dispatches_total"] = rec["d"]
            out["dispatches_delta"] = (
                rec["d"] - prev_d if prev_d is not None else rec["d"]
            )
            prev_d = rec["d"]
        if "x" in rec:
            out["transfers_total"] = rec["x"]
            if prev_x is not None:
                out["transfers_delta"] = {
                    k: rec["x"].get(k, 0) - prev_x.get(k, 0)
                    for k in rec["x"]
                }
            prev_x = rec["x"]
        if "hz" in rec:
            out["recompile_hazards_total"] = rec["hz"]
            out["recompile_hazards_delta"] = (
                rec["hz"] - prev_hz if prev_hz is not None else rec["hz"]
            )
            prev_hz = rec["hz"]
        if records and out["epoch"] is not None and not run_start:
            pe = records[-1]["epoch"]
            if pe is not None and out["epoch"] < pe:
                monotonic = False
        run_start = False
        records.append(out)
    if last is not None:
        # truncate AFTER deriving deltas/monotonicity over the whole
        # file: the first displayed record must carry its real
        # per-barrier delta, not the run's cumulative total
        records = records[-last:]
    return {
        "header": header,
        "records": records,
        "torn_lines": torn,
        "monotonic": monotonic,
        "source": paths,
    }


def records_to_trace_events(records: List[Dict]) -> List[tuple]:
    """Expanded reader records -> trace.render_chrome_trace event
    tuples: one slice per stage per barrier, laid out sequentially
    inside the barrier's wall window, carrying the epoch arg so the
    flow-event machinery links barriers across the timeline."""
    events: List[tuple] = []
    for rec in records:
        ts = rec.get("ts")
        wall_ms = rec.get("wall_ms") or 0.0
        if ts is None:
            continue
        t0 = ts - wall_ms / 1e3
        epoch = rec.get("epoch")
        events.append(
            (
                "barrier",
                1,
                t0,
                wall_ms / 1e3,
                {"epoch": epoch, "checkpoint": rec.get("checkpoint")},
            )
        )
        cursor = t0
        for stage, ms in (rec.get("stages_ms") or {}).items():
            events.append(
                ("stage." + stage, 2, cursor, ms / 1e3, {"epoch": epoch})
            )
            cursor += ms / 1e3
    return events
