"""Arrow interop — StreamChunk <-> pyarrow RecordBatch.

Reference: src/common/src/array/arrow/ (arrow conversions used by the
UDF boundary, iceberg/deltalake sinks, and connector parsers).

The device plane stays fixed-width lanes; Arrow is the HOST edge
format: converting OUT compacts live rows and decodes VARCHAR
dictionary codes to proper utf8 (or arrow dictionary arrays);
converting IN pads to chunk capacity and encodes strings through a
``StringDictionary``. NULL lanes map to arrow validity bitmaps both
ways.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from risingwave_tpu.array.chunk import StreamChunk
from risingwave_tpu.array.dictionary import StringDictionary
from risingwave_tpu.types import Op


def chunk_to_arrow(
    chunk: StreamChunk,
    dictionaries: Optional[Dict[str, StringDictionary]] = None,
    with_ops: bool = False,
):
    """Live rows -> pyarrow.RecordBatch; ``dictionaries`` maps VARCHAR
    column names to their code dictionaries (decoded to utf8)."""
    import pyarrow as pa

    data = chunk.to_numpy(with_ops=with_ops)
    names = [
        n
        for n in data
        if not n.endswith("__null") and n != "__op__"
    ]
    arrays, fields = [], []
    for n in names:
        col = data[n]
        mask = data.get(n + "__null")
        d = (dictionaries or {}).get(n)
        if d is not None:
            vals = d.decode(col.astype(np.int32))
            arr = pa.array(
                [None if mask is not None and mask[i] else vals[i]
                 for i in range(len(vals))],
                type=pa.string(),
            )
        else:
            arr = pa.array(col, mask=mask)
        arrays.append(arr)
        fields.append(pa.field(n, arr.type, nullable=mask is not None))
    if with_ops:
        arrays.append(pa.array(data["__op__"].astype(np.int8)))
        fields.append(pa.field("__op__", pa.int8(), nullable=False))
    return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))


def chunk_from_arrow(
    batch,
    capacity: Optional[int] = None,
    dictionaries: Optional[Dict[str, StringDictionary]] = None,
) -> StreamChunk:
    """pyarrow.RecordBatch -> StreamChunk; string columns encode through
    the provided (or fresh) dictionaries, ``__op__`` becomes the op
    lane."""
    import pyarrow as pa

    if dictionaries is None:
        dictionaries = {}
    n = batch.num_rows
    cap = capacity or max(2, 1 << max(0, (n - 1)).bit_length())
    cols: Dict[str, np.ndarray] = {}
    nulls: Dict[str, np.ndarray] = {}
    ops = None
    for name in batch.schema.names:
        arr = batch.column(name)
        if name == "__op__":
            ops = np.asarray(arr.to_numpy(zero_copy_only=False), np.int32)
            continue
        isnull = np.asarray(
            [not v for v in arr.is_valid().to_pylist()], bool
        )
        if pa.types.is_string(arr.type) or pa.types.is_large_string(arr.type):
            d = dictionaries.setdefault(name, StringDictionary())
            py = arr.to_pylist()
            cols[name] = d.encode(
                [("" if v is None else v) for v in py]
            ).astype(np.int32)
        else:
            cols[name] = np.asarray(
                arr.fill_null(0).to_numpy(zero_copy_only=False)
            )
        if isnull.any():
            nulls[name] = isnull
    if ops is None:
        ops_arr = np.full(n, int(Op.INSERT), np.int32)
    else:
        ops_arr = ops
    return StreamChunk.from_numpy(
        cols, cap, ops=ops_arr, nulls=nulls or None
    )
