"""Host-edge encoding of wide SQL types onto fixed-width device lanes.

Reference: src/common/src/types/ (ScalarImpl for decimal / interval /
jsonb / struct / list) and the per-type arrays in src/common/src/array/
(struct_array.rs, list_array.rs, jsonb_array.rs, decimal in
primitive_array.rs). The reference stores variable-width payloads in
heap buffers; TPU lanes must be fixed-width, so:

- DECIMAL(p, s): scaled int64 (``round(v * 10^s)``) — exact, and +/-/
  sum/compare work natively on the lane;
- INTERVAL: ``name.months`` int32 + ``name.usecs`` int64;
- JSONB: canonical JSON text (sort_keys) -> int32 code in a shared
  StringDictionary (equality on codes == jsonb equality);
- STRUCT: recursive decomposition into ``parent.child`` leaf lanes,
  plus a per-struct null lane when the struct itself is nullable;
- LIST: element lanes ``name.0`` .. ``name.<cap-1>`` + length lane
  ``name.#`` (pad-to-cap; rows whose list exceeds cap raise at encode).

``expand_field`` gives the lane layout; ``encode_rows``/``decode_rows``
convert python values <-> lane dicts for DML and SELECT edges.
"""

from __future__ import annotations

import json
from decimal import Decimal
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.array.dictionary import StringDictionary
from risingwave_tpu.types import DataType, Field, Interval

LIST_LEN_SUFFIX = ".#"


def _child_field(parent: Field, child: Field) -> Field:
    """Child Field re-rooted under its parent's lane prefix — the one
    place the prefixed reconstruction lives (expand/encode/decode all
    route through it, so new Field parameters thread automatically)."""
    return Field(
        f"{parent.name}.{child.name}",
        child.dtype,
        scale=child.scale,
        children=child.children,
        elem=child.elem,
        list_cap=child.list_cap,
    )


_I256_BITS = 256
_I256_LIMBS = 4
_U64_MASK = (1 << 64) - 1


def _int256_to_limbs(v: int) -> Tuple[int, ...]:
    """Signed 256-bit int -> 4 little-endian 64-bit limbs, each stored
    two's-complement in an int64 lane (reference: types/int256 — a
    4-limb wide integer; limb lanes keep device storage fixed-width)."""
    if not -(1 << 255) <= v < (1 << 255):
        raise OverflowError(f"{v} overflows INT256")
    u = v & ((1 << _I256_BITS) - 1)  # two's complement
    out = []
    for i in range(_I256_LIMBS):
        limb = (u >> (64 * i)) & _U64_MASK
        out.append(limb - (1 << 64) if limb >= (1 << 63) else limb)
    return tuple(out)


def _limbs_to_int256(limbs: Sequence[int]) -> int:
    u = 0
    for i, limb in enumerate(limbs):
        u |= (int(limb) & _U64_MASK) << (64 * i)
    return u - (1 << _I256_BITS) if u >= (1 << 255) else u


def expand_field(field: Field) -> List[Tuple[str, np.dtype]]:
    """Leaf device lanes (name, dtype) for one logical column."""
    dt = field.dtype
    if dt is DataType.INT256:
        return [
            (f"{field.name}.l{i}", np.dtype(np.int64))
            for i in range(_I256_LIMBS)
        ]
    if dt is DataType.INTERVAL:
        return [
            (f"{field.name}.months", np.dtype(np.int32)),
            (f"{field.name}.usecs", np.dtype(np.int64)),
        ]
    if dt is DataType.STRUCT:
        out: List[Tuple[str, np.dtype]] = []
        for child in field.children:
            out.extend(expand_field(_child_field(field, child)))
        return out
    if dt is DataType.LIST:
        ed = field.elem.device_dtype
        lanes = [
            (f"{field.name}.{i}", ed) for i in range(field.list_cap)
        ]
        lanes.append((field.name + LIST_LEN_SUFFIX, np.dtype(np.int32)))
        return lanes
    return [(field.name, dt.device_dtype)]


def _dec_to_scaled(v, scale: int) -> int:
    if isinstance(v, Decimal):
        q = v.scaleb(scale)
    elif isinstance(v, str):
        q = Decimal(v).scaleb(scale)
    else:
        q = Decimal(repr(v)).scaleb(scale)
    return int(q.to_integral_value())


def encode_column(
    field: Field,
    values: Sequence,
    strings: Optional[StringDictionary] = None,
) -> Tuple[Dict[str, np.ndarray], Optional[Dict[str, np.ndarray]]]:
    """python values -> {lane: array}, plus null lanes ({lane: bool[]}
    or None). NULL python value = None. Composite children may be
    individually NULL via None inside the composite value."""
    n = len(values)
    dt = field.dtype
    isnull = np.asarray([v is None for v in values], bool)
    # null lanes must ride a real device lane: composites anchor theirs
    # on a designated leaf (interval -> .usecs, list -> .#); a NULL
    # struct marks every child NULL (no struct-level lane exists)
    anchor = field.name
    if dt is DataType.INTERVAL:
        anchor = f"{field.name}.usecs"
    elif dt is DataType.LIST:
        anchor = field.name + LIST_LEN_SUFFIX
    elif dt is DataType.INT256:
        anchor = f"{field.name}.l0"
    nulls = {anchor: isnull} if isnull.any() else None

    if dt is DataType.VARCHAR or dt is DataType.JSONB:
        if strings is None:
            raise ValueError(f"{dt} column {field.name!r} needs a dictionary")
        texts = [
            ""
            if v is None
            else (
                v
                if dt is DataType.VARCHAR
                else json.dumps(v, sort_keys=True, separators=(",", ":"))
            )
            for v in values
        ]
        return {field.name: strings.encode(texts)}, nulls
    if dt is DataType.DECIMAL:
        arr = np.asarray(
            [
                0 if v is None else _dec_to_scaled(v, field.scale)
                for v in values
            ],
            np.int64,
        )
        return {field.name: arr}, nulls
    if dt is DataType.INT256:
        limb_arrs = [np.zeros(n, np.int64) for _ in range(_I256_LIMBS)]
        for i, v in enumerate(values):
            if v is None:
                continue
            for j, limb in enumerate(_int256_to_limbs(int(v))):
                limb_arrs[j][i] = limb
        return {
            f"{field.name}.l{j}": limb_arrs[j]
            for j in range(_I256_LIMBS)
        }, nulls
    if dt is DataType.INTERVAL:
        months = np.zeros(n, np.int32)
        usecs = np.zeros(n, np.int64)
        for i, v in enumerate(values):
            if v is None:
                continue
            if not isinstance(v, Interval):
                raise TypeError(f"expected Interval, got {type(v)}")
            months[i] = v.months
            usecs[i] = v.usecs
        lanes = {
            f"{field.name}.months": months,
            f"{field.name}.usecs": usecs,
        }
        return lanes, nulls
    if dt is DataType.STRUCT:
        lanes: Dict[str, np.ndarray] = {}
        all_nulls: Dict[str, np.ndarray] = {}
        for child in field.children:
            cvals = [
                None if v is None else v.get(child.name) for v in values
            ]
            clanes, cnulls = encode_column(
                _child_field(field, child), cvals, strings
            )
            lanes.update(clanes)
            if cnulls:
                all_nulls.update(cnulls)
        return lanes, all_nulls or None
    if dt is DataType.LIST:
        cap = field.list_cap
        ed = field.elem.device_dtype
        lens = np.zeros(n, np.int32)
        elems = np.zeros((cap, n), ed)
        for i, v in enumerate(values):
            if v is None:
                continue
            if len(v) > cap:
                raise ValueError(
                    f"list in {field.name!r} has {len(v)} elements, "
                    f"cap is {cap}"
                )
            lens[i] = len(v)
            for j, e in enumerate(v):
                elems[j, i] = e
        lanes = {f"{field.name}.{i}": elems[i] for i in range(cap)}
        lanes[field.name + LIST_LEN_SUFFIX] = lens
        return lanes, nulls

    arr = np.asarray(
        [dt.null_value if v is None else v for v in values],
        dt.device_dtype,
    )
    return {field.name: arr}, nulls


def decode_column(
    field: Field,
    lanes: Dict[str, np.ndarray],
    null_of,
    strings: Optional[StringDictionary] = None,
) -> List:
    """{lane: array} -> python values. ``null_of(lane_name)`` returns a
    bool array (or None) marking SQL NULLs for a lane."""
    dt = field.dtype
    if dt is DataType.INTERVAL:
        isnull = null_of(f"{field.name}.usecs")
    elif dt is DataType.LIST:
        isnull = null_of(field.name + LIST_LEN_SUFFIX)
    elif dt is DataType.STRUCT:
        isnull = None  # NULL struct == all children NULL
    elif dt is DataType.INT256:
        isnull = null_of(f"{field.name}.l0")
    else:
        isnull = null_of(field.name)

    def _masked(vals):
        if isnull is None:
            return list(vals)
        return [None if m else v for v, m in zip(vals, isnull)]

    if dt is DataType.VARCHAR:
        return _masked(strings.decode(lanes[field.name]).tolist())
    if dt is DataType.JSONB:
        texts = strings.decode(lanes[field.name])
        if isnull is None:
            return [json.loads(s) for s in texts]
        # NULL rows encode as "" — mask BEFORE parsing
        return [
            None if m else json.loads(s) for s, m in zip(texts, isnull)
        ]
    if dt is DataType.DECIMAL:
        return _masked(
            [
                Decimal(int(v)).scaleb(-field.scale)
                for v in lanes[field.name]
            ]
        )
    if dt is DataType.INT256:
        limb_arrs = [
            lanes[f"{field.name}.l{j}"] for j in range(_I256_LIMBS)
        ]
        return _masked(
            [
                _limbs_to_int256([a[i] for a in limb_arrs])
                for i in range(len(limb_arrs[0]))
            ]
        )
    if dt is DataType.INTERVAL:
        months = lanes[f"{field.name}.months"]
        usecs = lanes[f"{field.name}.usecs"]
        return _masked(
            [Interval(int(m), int(u)) for m, u in zip(months, usecs)]
        )
    if dt is DataType.STRUCT:
        per_child = {}
        for child in field.children:
            per_child[child.name] = decode_column(
                _child_field(field, child), lanes, null_of, strings
            )
        n = len(next(iter(per_child.values())))
        rows = [
            {k: per_child[k][i] for k in per_child} for i in range(n)
        ]
        return _masked(rows)
    if dt is DataType.LIST:
        lens = lanes[field.name + LIST_LEN_SUFFIX]
        elem_lanes = [
            lanes[f"{field.name}.{i}"] for i in range(field.list_cap)
        ]
        py = field.elem.device_dtype.type
        rows = [
            [py(elem_lanes[j][i]).item() for j in range(int(lens[i]))]
            for i in range(len(lens))
        ]
        return _masked(rows)
    vals = lanes[field.name]
    if dt is DataType.BOOLEAN:
        return _masked([bool(v) for v in vals])
    return _masked([v.item() for v in np.asarray(vals)])


def encode_rows(
    schema,
    rows: Sequence[Sequence],
    strings: Optional[StringDictionary] = None,
):
    """Row tuples (schema order) -> (lanes, null_lanes) column dicts."""
    lanes: Dict[str, np.ndarray] = {}
    nulls: Dict[str, np.ndarray] = {}
    for j, field in enumerate(schema):
        vals = [r[j] for r in rows]
        cl, cn = encode_column(field, vals, strings)
        lanes.update(cl)
        if cn:
            nulls.update(cn)
    return lanes, nulls or None
