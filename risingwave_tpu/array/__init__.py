from risingwave_tpu.array.chunk import DataChunk, StreamChunk

__all__ = ["DataChunk", "StreamChunk"]
