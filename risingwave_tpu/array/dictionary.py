"""Host-side string dictionary — VARCHAR's device representation.

Reference: src/common/src/array/utf8_array.rs stores UTF-8 payloads in a
variable-length buffer; variable-length data is hostile to TPU lanes, so
the TPU plane carries VARCHAR as int32 *dictionary codes* (types.py) and
the code<->string mapping lives host-side in this module.

Properties that make this sound for streaming SQL:
- append-only: a code, once assigned, never changes — device state
  (group keys, join keys, materialized payloads) referencing a code
  stays valid across epochs;
- equality-complete: two rows carry the same code iff they carry the
  same string, so device-side hash/compare on the code column IS string
  equality (group-by / equi-join on VARCHAR needs nothing else);
- checkpointable: the dictionary serializes with the operator state so
  recovery restores code stability (state/ persists it alongside table
  snapshots).

Codes are NOT order-preserving; ORDER BY / range predicates on VARCHAR
must decode host-side (or use a future sorted-dictionary build).
"""

from __future__ import annotations

import threading
from typing import Iterable, List, Sequence

import numpy as np


class StringDictionary:
    """Bidirectional append-only str <-> int32 code mapping.

    Thread-safe on the encode path: the serving tier typechecks
    SELECTs (which may encode novel string literals) OUTSIDE the
    runtime lock, concurrently with DML encoding under it — the
    check-then-act code assignment must be atomic or two threads can
    mint the same code for different strings (permanent corruption of
    everything keyed on the code). Decode stays lock-free: codes are
    append-only and list reads are atomic under the GIL."""

    def __init__(self, values: Iterable[str] = ()):  # restore path
        self._strings: List[str] = []
        self._codes: dict[str, int] = {}
        self._table: np.ndarray | None = None  # decode cache
        self._lock = threading.Lock()
        for s in values:
            self.encode_one(s)

    def __len__(self) -> int:
        return len(self._strings)

    def encode_one(self, s: str) -> int:
        code = self._codes.get(s)  # lock-free hit: codes never change
        if code is None:
            with self._lock:
                code = self._codes.get(s)
                if code is None:
                    code = len(self._strings)
                    self._codes[s] = code
                    self._strings.append(s)
        return code

    def encode(self, values: Sequence[str]) -> np.ndarray:
        """Vector encode; assigns fresh codes to unseen strings."""
        return np.fromiter(
            (self.encode_one(s) for s in values), dtype=np.int32, count=len(values)
        )

    def decode_one(self, code: int) -> str:
        return self._strings[code]

    def decode(self, codes: np.ndarray) -> np.ndarray:
        """Vector decode to a numpy object array of str."""
        # cache the lookup table; rebuild only after growth (decoding a
        # few codes per barrier must not pay O(dictionary) each time)
        if self._table is None or len(self._table) != len(self._strings):
            self._table = np.asarray(self._strings, dtype=object)
        return self._table[np.asarray(codes, dtype=np.int64)]

    # -- persistence (used by state checkpointing) ----------------------
    def dump(self) -> List[str]:
        """Code-ordered string list; feed back to __init__ to restore."""
        return list(self._strings)
