"""Columnar chunk model — the unit of dataflow.

Reference: src/common/src/array/data_chunk.rs (DataChunk = columns +
visibility bitmap) and src/common/src/array/stream_chunk.rs:98
(StreamChunk = DataChunk + ops column).

TPU-first re-design: a chunk is a *fixed-capacity* struct-of-arrays.
Row count never appears in any array shape — instead a boolean ``valid``
lane marks live rows and padding lanes carry null values. This is what
lets an entire fragment chain compile once under ``jax.jit`` and re-run
every epoch with zero recompiles (XLA requires static shapes; see
SURVEY.md §7 "Dynamic shapes vs. XLA").

Chunks are registered pytrees, so they flow through ``jit`` /
``shard_map`` / ``lax.scan`` directly, and the column dict maps onto
``jax.sharding`` PartitionSpecs per column for the vnode-sharded
multi-chip path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.types import DataType, Op, Schema, op_sign


@jax.tree_util.register_pytree_node_class
@dataclass
class DataChunk:
    """Fixed-capacity columnar batch with a validity (visibility) mask.

    ``columns`` maps column name -> (capacity,) device array.
    ``valid`` is the visibility bitmap (reference: data_chunk.rs
    ``Bitmap``), also covering padding lanes.
    """

    columns: Dict[str, jnp.ndarray]
    valid: jnp.ndarray  # (capacity,) bool

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        names = tuple(sorted(self.columns))
        return (tuple(self.columns[n] for n in names) + (self.valid,), names)

    @classmethod
    def tree_unflatten(cls, names, children):
        *cols, valid = children
        return cls(columns=dict(zip(names, cols)), valid=valid)

    # -- basics ---------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.valid.shape[0]

    def num_rows(self) -> jnp.ndarray:
        """Dynamic count of live rows (a traced scalar under jit)."""
        return jnp.sum(self.valid.astype(jnp.int32))

    def col(self, name: str) -> jnp.ndarray:
        return self.columns[name]

    def with_columns(self, **cols: jnp.ndarray) -> "DataChunk":
        new = dict(self.columns)
        new.update(cols)
        return DataChunk(new, self.valid)

    def select(self, names) -> "DataChunk":
        return DataChunk({n: self.columns[n] for n in names}, self.valid)

    def rename(self, mapping: Mapping[str, str]) -> "DataChunk":
        return DataChunk(
            {mapping.get(n, n): a for n, a in self.columns.items()}, self.valid
        )

    def mask(self, keep: jnp.ndarray) -> "DataChunk":
        """Narrow visibility (filter) without moving data."""
        return DataChunk(self.columns, self.valid & keep)

    # -- host interop ---------------------------------------------------
    @staticmethod
    def from_numpy(
        cols: Mapping[str, np.ndarray], capacity: int, schema: Optional[Schema] = None
    ) -> "DataChunk":
        n = _common_len(cols)
        if n > capacity:
            raise ValueError(f"{n} rows exceed capacity {capacity}")
        out = {}
        for name, arr in cols.items():
            arr = np.asarray(arr)
            dtype = (
                schema.field(name).dtype.device_dtype if schema is not None else arr.dtype
            )
            pad = np.zeros(capacity, dtype=dtype)
            pad[:n] = arr.astype(dtype)
            out[name] = jnp.asarray(pad)
        valid = np.zeros(capacity, dtype=np.bool_)
        valid[:n] = True
        return DataChunk(out, jnp.asarray(valid))

    def to_numpy(self) -> Dict[str, np.ndarray]:
        """Compact live rows back to host (drops padding)."""
        valid = np.asarray(self.valid)
        return {n: np.asarray(a)[valid] for n, a in self.columns.items()}


@jax.tree_util.register_pytree_node_class
@dataclass
class StreamChunk(DataChunk):
    """DataChunk + per-row change op (reference: stream_chunk.rs:98)."""

    ops: jnp.ndarray  # (capacity,) int32 of types.Op — required; use
    # ``from_data``/``from_numpy`` to default to all-INSERT

    def tree_flatten(self):
        names = tuple(sorted(self.columns))
        return (
            tuple(self.columns[n] for n in names) + (self.valid, self.ops),
            names,
        )

    @classmethod
    def tree_unflatten(cls, names, children):
        *cols, valid, ops = children
        return cls(columns=dict(zip(names, cols)), valid=valid, ops=ops)

    # -- construction ---------------------------------------------------
    @staticmethod
    def from_data(chunk: DataChunk, ops: Optional[jnp.ndarray] = None) -> "StreamChunk":
        if ops is None:
            ops = jnp.zeros(chunk.capacity, dtype=jnp.int32)  # all INSERT
        return StreamChunk(columns=chunk.columns, valid=chunk.valid, ops=ops)

    @staticmethod
    def from_numpy(
        cols: Mapping[str, np.ndarray],
        capacity: int,
        ops: Optional[np.ndarray] = None,
        schema: Optional[Schema] = None,
    ) -> "StreamChunk":
        base = DataChunk.from_numpy(cols, capacity, schema)
        if ops is None:
            dev_ops = jnp.zeros(capacity, dtype=jnp.int32)
        else:
            pad = np.zeros(capacity, dtype=np.int32)
            pad[: len(ops)] = np.asarray(ops, dtype=np.int32)
            dev_ops = jnp.asarray(pad)
        return StreamChunk(columns=base.columns, valid=base.valid, ops=dev_ops)

    # -- semantics ------------------------------------------------------
    def signs(self) -> jnp.ndarray:
        """+1 / -1 per row; 0 contribution is handled via ``valid``."""
        return op_sign(self.ops)

    def effective_signs(self) -> jnp.ndarray:
        """Signs with padding zeroed — the canonical retraction weight."""
        return jnp.where(self.valid, self.signs(), jnp.int32(0))

    def with_columns(self, **cols: jnp.ndarray) -> "StreamChunk":
        new = dict(self.columns)
        new.update(cols)
        return StreamChunk(new, self.valid, self.ops)

    def select(self, names) -> "StreamChunk":
        return StreamChunk({n: self.columns[n] for n in names}, self.valid, self.ops)

    def rename(self, mapping: Mapping[str, str]) -> "StreamChunk":
        return StreamChunk(
            {mapping.get(n, n): a for n, a in self.columns.items()},
            self.valid,
            self.ops,
        )

    def mask(self, keep: jnp.ndarray) -> "StreamChunk":
        return StreamChunk(self.columns, self.valid & keep, self.ops)

    def to_numpy(self, with_ops: bool = True) -> Dict[str, np.ndarray]:
        out = super().to_numpy()
        if with_ops:
            out["__op__"] = np.asarray(self.ops)[np.asarray(self.valid)]
        return out


def _common_len(cols: Mapping[str, np.ndarray]) -> int:
    lens = {len(np.asarray(a)) for a in cols.values()}
    if len(lens) > 1:
        raise ValueError(f"ragged columns: {lens}")
    return lens.pop() if lens else 0


def concat_chunks(chunks, capacity: Optional[int] = None) -> StreamChunk:
    """Host-side helper: stack chunks into one wider chunk (test utility)."""
    nps = [c.to_numpy(with_ops=True) for c in chunks]
    names = [n for n in nps[0] if n != "__op__"]
    cols = {n: np.concatenate([d[n] for d in nps]) for n in names}
    ops = np.concatenate([d["__op__"] for d in nps])
    cap = capacity or max(1, len(ops))
    return StreamChunk.from_numpy(cols, cap, ops=ops)
