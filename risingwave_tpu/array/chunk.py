"""Columnar chunk model — the unit of dataflow.

Reference: src/common/src/array/data_chunk.rs (DataChunk = columns +
visibility bitmap) and src/common/src/array/stream_chunk.rs:98
(StreamChunk = DataChunk + ops column).

TPU-first re-design: a chunk is a *fixed-capacity* struct-of-arrays.
Row count never appears in any array shape — instead a boolean ``valid``
lane marks live rows and padding lanes carry null values. This is what
lets an entire fragment chain compile once under ``jax.jit`` and re-run
every epoch with zero recompiles (XLA requires static shapes; see
SURVEY.md §7 "Dynamic shapes vs. XLA").

Nullability is per-column, separate from row visibility (mirroring the
reference where every array carries its own null ``Bitmap`` while the
chunk carries visibility, data_chunk.rs): ``nulls[name]`` is a bool lane
(True = SQL NULL) present only for columns that can hold NULLs. A row can
be visible yet hold NULL in some column — r1 conflated the two, making
SQL NULL semantics inexpressible (VERDICT r1 weak #3).

Chunks are registered pytrees, so they flow through ``jit`` /
``shard_map`` / ``lax.scan`` directly, and the column dict maps onto
``jax.sharding`` PartitionSpecs per column for the vnode-sharded
multi-chip path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.types import Schema, op_sign


@jax.tree_util.register_pytree_node_class
@dataclass
class DataChunk:
    """Fixed-capacity columnar batch with visibility + per-column nulls.

    ``columns`` maps column name -> (capacity,) device array.
    ``valid`` is the visibility bitmap (reference: data_chunk.rs
    ``Bitmap``), also covering padding lanes.
    ``nulls`` maps a SUBSET of column names -> (capacity,) bool array
    where True marks SQL NULL; columns absent from ``nulls`` are
    non-nullable.
    """

    columns: Dict[str, jnp.ndarray]
    valid: jnp.ndarray  # (capacity,) bool
    nulls: Dict[str, jnp.ndarray] = field(default_factory=dict)

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        names = tuple(sorted(self.columns))
        null_names = tuple(sorted(self.nulls))
        children = (
            tuple(self.columns[n] for n in names)
            + tuple(self.nulls[n] for n in null_names)
            + (self.valid,)
        )
        return children, (names, null_names)

    @classmethod
    def tree_unflatten(cls, aux, children):
        names, null_names = aux
        cols = children[: len(names)]
        nulls = children[len(names) : len(names) + len(null_names)]
        valid = children[-1]
        return cls(
            columns=dict(zip(names, cols)),
            valid=valid,
            nulls=dict(zip(null_names, nulls)),
        )

    # -- basics ---------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.valid.shape[0]

    def num_rows(self) -> jnp.ndarray:
        """Dynamic count of live rows (a traced scalar under jit)."""
        return jnp.sum(self.valid.astype(jnp.int32))

    def col(self, name: str) -> jnp.ndarray:
        return self.columns[name]

    def null_of(self, name: str) -> jnp.ndarray:
        """Null lane for a column; all-False lane if non-nullable."""
        lane = self.nulls.get(name)
        if lane is None:
            return jnp.zeros(self.capacity, jnp.bool_)
        return lane

    def is_nullable(self, name: str) -> bool:
        return name in self.nulls

    def with_columns(self, **cols: jnp.ndarray) -> "DataChunk":
        """Add/replace columns. Replaced columns become NON-nullable —
        computed values carry no NULLs unless re-marked via
        ``with_nulls`` (keeping a stale null lane would silently send
        fresh values to the NULL group)."""
        new = dict(self.columns)
        new.update(cols)
        nulls = {n: a for n, a in self.nulls.items() if n not in cols}
        return DataChunk(new, self.valid, nulls)

    def with_nulls(self, **lanes: jnp.ndarray) -> "DataChunk":
        new = dict(self.nulls)
        new.update(lanes)
        return DataChunk(self.columns, self.valid, new)

    def select(self, names) -> "DataChunk":
        return DataChunk(
            {n: self.columns[n] for n in names},
            self.valid,
            {n: self.nulls[n] for n in names if n in self.nulls},
        )

    def rename(self, mapping: Mapping[str, str]) -> "DataChunk":
        return DataChunk(
            {mapping.get(n, n): a for n, a in self.columns.items()},
            self.valid,
            {mapping.get(n, n): a for n, a in self.nulls.items()},
        )

    def mask(self, keep: jnp.ndarray) -> "DataChunk":
        """Narrow visibility (filter) without moving data."""
        return DataChunk(self.columns, self.valid & keep, self.nulls)

    # -- host interop ---------------------------------------------------
    @staticmethod
    def from_numpy(
        cols: Mapping[str, np.ndarray],
        capacity: int,
        schema: Optional[Schema] = None,
        nulls: Optional[Mapping[str, np.ndarray]] = None,
    ) -> "DataChunk":
        n = _common_len(cols)
        if n > capacity:
            raise ValueError(f"{n} rows exceed capacity {capacity}")
        out = {}
        for name, arr in cols.items():
            arr = np.asarray(arr)
            dtype = (
                schema.field(name).dtype.device_dtype if schema is not None else arr.dtype
            )
            if (
                np.issubdtype(arr.dtype, np.integer)
                and np.issubdtype(dtype, np.integer)
                and arr.size
                and (
                    arr.max(initial=0) > np.iinfo(dtype).max
                    or arr.min(initial=0) < np.iinfo(dtype).min
                )
            ):
                raise ValueError(
                    f"column {name!r}: values overflow device dtype {dtype}"
                )
            pad = np.zeros(capacity, dtype=dtype)
            pad[:n] = arr.astype(dtype)
            out[name] = jnp.asarray(pad)
        valid = np.zeros(capacity, dtype=np.bool_)
        valid[:n] = True
        dev_nulls = {}
        for name, lane in (nulls or {}).items():
            if name not in out:
                raise KeyError(f"null lane for unknown column {name!r}")
            pad = np.zeros(capacity, dtype=np.bool_)
            pad[:n] = np.asarray(lane, dtype=np.bool_)
            dev_nulls[name] = jnp.asarray(pad)
        return DataChunk(out, jnp.asarray(valid), dev_nulls)

    def _live_slice(self):
        """(valid_prefix, pad): transfer the 1-byte valid lane first,
        then move only the prefix holding live rows — emission chunks
        compact valid rows to the front (compact_pairs / agg flush), so
        this turns O(capacity) device->host copies into O(live). The
        pow2 pad bounds distinct slice programs; scattered-valid chunks
        degrade to the full copy, never worse."""
        valid = np.asarray(self.valid)
        nz = np.flatnonzero(valid)
        if len(nz) == 0:
            return valid[:0], 0
        k = int(nz[-1]) + 1
        pad = min(len(valid), max(2, 1 << (k - 1).bit_length()))
        return valid[:pad], pad

    def to_numpy(self) -> Dict[str, np.ndarray]:
        """Compact live rows back to host (drops padding).

        NULL lanes come back as ``<name>__null`` bool columns.
        """
        valid, pad = self._live_slice()
        out = {
            n: np.asarray(a[:pad])[valid] for n, a in self.columns.items()
        }
        for n, lane in self.nulls.items():
            out[n + "__null"] = np.asarray(lane[:pad])[valid]
        return out


@jax.tree_util.register_pytree_node_class
@dataclass
class StreamChunk(DataChunk):
    """DataChunk + per-row change op (reference: stream_chunk.rs:98)."""

    ops: jnp.ndarray = None  # (capacity,) int32 of types.Op; required —
    # dataclass inheritance forces a default, __post_init__ rejects None

    def __post_init__(self):
        if self.ops is None:
            raise TypeError(
                "StreamChunk.ops is required; use from_data/from_numpy "
                "to default to all-INSERT"
            )

    def tree_flatten(self):
        names = tuple(sorted(self.columns))
        null_names = tuple(sorted(self.nulls))
        children = (
            tuple(self.columns[n] for n in names)
            + tuple(self.nulls[n] for n in null_names)
            + (self.valid, self.ops)
        )
        return children, (names, null_names)

    @classmethod
    def tree_unflatten(cls, aux, children):
        names, null_names = aux
        cols = children[: len(names)]
        nulls = children[len(names) : len(names) + len(null_names)]
        valid, ops = children[-2], children[-1]
        return cls(
            columns=dict(zip(names, cols)),
            valid=valid,
            nulls=dict(zip(null_names, nulls)),
            ops=ops,
        )

    # -- construction ---------------------------------------------------
    @staticmethod
    def from_data(chunk: DataChunk, ops: Optional[jnp.ndarray] = None) -> "StreamChunk":
        if ops is None:
            ops = jnp.zeros(chunk.capacity, dtype=jnp.int32)  # all INSERT
        return StreamChunk(
            columns=chunk.columns, valid=chunk.valid, nulls=chunk.nulls, ops=ops
        )

    @staticmethod
    def from_numpy(
        cols: Mapping[str, np.ndarray],
        capacity: int,
        ops: Optional[np.ndarray] = None,
        schema: Optional[Schema] = None,
        nulls: Optional[Mapping[str, np.ndarray]] = None,
    ) -> "StreamChunk":
        base = DataChunk.from_numpy(cols, capacity, schema, nulls)
        if ops is None:
            dev_ops = jnp.zeros(capacity, dtype=jnp.int32)
        else:
            pad = np.zeros(capacity, dtype=np.int32)
            pad[: len(ops)] = np.asarray(ops, dtype=np.int32)
            dev_ops = jnp.asarray(pad)
        return StreamChunk(
            columns=base.columns, valid=base.valid, nulls=base.nulls, ops=dev_ops
        )

    # -- semantics ------------------------------------------------------
    def signs(self) -> jnp.ndarray:
        """+1 / -1 per row; 0 contribution is handled via ``valid``."""
        return op_sign(self.ops)

    def effective_signs(self) -> jnp.ndarray:
        """Signs with padding zeroed — the canonical retraction weight."""
        return jnp.where(self.valid, self.signs(), jnp.int32(0))

    def with_columns(self, **cols: jnp.ndarray) -> "StreamChunk":
        new = dict(self.columns)
        new.update(cols)
        nulls = {n: a for n, a in self.nulls.items() if n not in cols}
        return StreamChunk(new, self.valid, nulls, self.ops)

    def with_nulls(self, **lanes: jnp.ndarray) -> "StreamChunk":
        new = dict(self.nulls)
        new.update(lanes)
        return StreamChunk(self.columns, self.valid, new, self.ops)

    def select(self, names) -> "StreamChunk":
        return StreamChunk(
            {n: self.columns[n] for n in names},
            self.valid,
            {n: self.nulls[n] for n in names if n in self.nulls},
            self.ops,
        )

    def rename(self, mapping: Mapping[str, str]) -> "StreamChunk":
        return StreamChunk(
            {mapping.get(n, n): a for n, a in self.columns.items()},
            self.valid,
            {mapping.get(n, n): a for n, a in self.nulls.items()},
            self.ops,
        )

    def mask(self, keep: jnp.ndarray) -> "StreamChunk":
        return StreamChunk(self.columns, self.valid & keep, self.nulls, self.ops)

    def to_numpy(self, with_ops: bool = True) -> Dict[str, np.ndarray]:
        out = super().to_numpy()
        if with_ops:
            valid, pad = self._live_slice()
            out["__op__"] = np.asarray(self.ops[:pad])[valid]
        return out


def _common_len(cols: Mapping[str, np.ndarray]) -> int:
    lens = {len(np.asarray(a)) for a in cols.values()}
    if len(lens) > 1:
        raise ValueError(f"ragged columns: {lens}")
    return lens.pop() if lens else 0


def concat_chunks(chunks, capacity: Optional[int] = None) -> StreamChunk:
    """Host-side helper: stack chunks into one wider chunk (test utility)."""
    nps = [c.to_numpy(with_ops=True) for c in chunks]
    names = [n for n in nps[0] if n != "__op__" and not n.endswith("__null")]
    # nullability may differ per chunk: union the null columns, treating
    # chunks without a lane as all-non-NULL
    null_names = sorted(
        {n[: -len("__null")] for d in nps for n in d if n.endswith("__null")}
    )
    cols = {n: np.concatenate([d[n] for d in nps]) for n in names}
    nulls = {
        n: np.concatenate(
            [
                d.get(n + "__null", np.zeros(len(d[n]), np.bool_))
                for d in nps
            ]
        )
        for n in null_names
    }
    ops = np.concatenate([d["__op__"] for d in nps])
    cap = capacity or max(1, len(ops))
    return StreamChunk.from_numpy(cols, cap, ops=ops, nulls=nulls or None)
