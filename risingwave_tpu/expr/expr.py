"""Expression AST evaluated column-at-a-time on DataChunks.

Reference: the ``Expression`` trait (src/expr/core/src/expr/) evaluates
on a whole DataChunk; scalar kernels come from the #[function] macro
(src/expr/macro/src/). Here every node is a dataclass whose ``eval``
is pure jnp, so whole expression trees fuse under ``jax.jit``.

NULL semantics:
- arithmetic / comparison are NULL-strict: any NULL input -> NULL out;
- AND / OR implement SQL three-valued logic
  (TRUE OR NULL = TRUE, FALSE AND NULL = FALSE, else NULL);
- predicates used by Filter keep only rows that are TRUE (NULL drops),
  matching the reference FilterExecutor (src/stream/src/executor/filter.rs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Tuple

import jax.numpy as jnp

from risingwave_tpu.array.chunk import DataChunk

# (values, null_lane) — null lane may be None meaning "no NULLs"
EvalResult = Tuple[jnp.ndarray, Optional[jnp.ndarray]]


def _null_or(a: Optional[jnp.ndarray], b: Optional[jnp.ndarray]):
    if a is None:
        return b
    if b is None:
        return a
    return a | b


class Expr:
    """Base node. Subclasses implement ``eval(chunk) -> EvalResult``."""

    def eval(self, chunk: DataChunk) -> EvalResult:  # pragma: no cover
        raise NotImplementedError

    # -- operator sugar --------------------------------------------------
    def __add__(self, o):
        return BinOp("+", self, _wrap(o))

    def __sub__(self, o):
        return BinOp("-", self, _wrap(o))

    def __mul__(self, o):
        return BinOp("*", self, _wrap(o))

    def __floordiv__(self, o):
        return BinOp("//", self, _wrap(o))

    def __mod__(self, o):
        return BinOp("%", self, _wrap(o))

    def __eq__(self, o):  # type: ignore[override]
        return BinOp("==", self, _wrap(o))

    def __ne__(self, o):  # type: ignore[override]
        return BinOp("!=", self, _wrap(o))

    def __lt__(self, o):
        return BinOp("<", self, _wrap(o))

    def __le__(self, o):
        return BinOp("<=", self, _wrap(o))

    def __gt__(self, o):
        return BinOp(">", self, _wrap(o))

    def __ge__(self, o):
        return BinOp(">=", self, _wrap(o))

    def __and__(self, o):
        return And(self, _wrap(o))

    def __or__(self, o):
        return Or(self, _wrap(o))

    def __invert__(self):
        return Not(self)

    __hash__ = object.__hash__  # __eq__ override would otherwise kill it


def structural_key(v) -> tuple:
    """Hashable STRUCTURAL identity of an expression tree.

    ``Expr.__eq__`` is operator sugar — ``a == b`` BUILDS ``BinOp``
    (always truthy) — so Exprs must never be compared with ``==`` for
    caching. In particular, passing a bare Expr (or a container of
    them) as a jit static argument silently collides different
    predicates in the compilation cache: the fastpath confirms a probe
    with ``==``, the truthy BinOp reads as "equal", and a second
    filter reuses the first predicate's kernel (observed: two MVs with
    different WHERE clauses returning identical rows). Wrap statics in
    ``StaticTree`` instead."""
    import dataclasses as _dc

    if isinstance(v, Expr):
        return (type(v).__name__,) + tuple(
            structural_key(getattr(v, f.name)) for f in _dc.fields(v)
        )
    if isinstance(v, (tuple, list)):
        return ("#seq",) + tuple(structural_key(x) for x in v)
    if isinstance(v, dict):
        return ("#map",) + tuple(
            (structural_key(k), structural_key(x))
            for k, x in sorted(v.items(), key=lambda kv: repr(kv[0]))
        )
    return ("#leaf", type(v).__name__, v)


def collect_columns(node) -> frozenset:
    """Every input column name an expression tree reads (the lint
    surface behind ``Executor.lint_info`` requires-sets). Walks any
    Expr dataclass plus tuple/list containers; never uses ``==`` on
    Exprs (see structural_key)."""
    import dataclasses as _dc

    out = set()

    def walk(x):
        if isinstance(x, Col):
            out.add(x.name)
            return
        if isinstance(x, Expr):
            if _dc.is_dataclass(x):
                for f in _dc.fields(x):
                    walk(getattr(x, f.name))
            return
        if isinstance(x, (tuple, list)):
            for v in x:
                walk(v)

    walk(node)
    return frozenset(out)


class StaticTree:
    """jit-static wrapper giving an Expr-bearing value structural
    eq/hash (see structural_key)."""

    __slots__ = ("value", "_key")

    def __init__(self, value):
        self.value = value
        self._key = structural_key(value)

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, StaticTree) and self._key == other._key

    def __ne__(self, other):
        return not self.__eq__(other)


def _wrap(v) -> "Expr":
    return v if isinstance(v, Expr) else Lit(v)


def col(name: str) -> "Col":
    return Col(name)


def lit(v) -> "Lit":
    return Lit(v)


@dataclass(frozen=True, eq=False)
class Col(Expr):
    name: str

    def eval(self, chunk: DataChunk) -> EvalResult:
        return chunk.col(self.name), chunk.nulls.get(self.name)


@dataclass(frozen=True, eq=False)
class Lit(Expr):
    value: object  # python scalar; None = SQL NULL literal

    def eval(self, chunk: DataChunk) -> EvalResult:
        if self.value is None:
            zero = jnp.zeros(chunk.capacity, jnp.int32)
            return zero, jnp.ones(chunk.capacity, jnp.bool_)
        return jnp.full(chunk.capacity, self.value), None


# -- lifted literals (multi-tenant compile sharing) ---------------------
#
# Two structurally-identical plans that differ ONLY in literal values
# (q5 twins with different thresholds, per-tenant parameterized MVs)
# would compile two distinct fused programs — the literal is baked
# into the jit-static expression tree. ``lift_literals`` rewrites
# numeric Lits into slot references against an ambient parameter
# vector that enters the fused program as a RUNTIME OPERAND, so K
# parameter variants share ONE compiled executable. The fused step
# proves dtype-equivalence (eval_shape) before trusting a lifted tree
# — weak-vs-strong scalar promotion can differ, and a mismatch falls
# back to the baked literal (correctness over sharing).

import threading as _threading
from contextlib import contextmanager

_PARAM_ENV = _threading.local()


def params_active() -> bool:
    """True while a (non-empty) lifted-literal param scope is bound —
    the one situation where a nested jit call must be inlined (its
    jaxpr cache cannot key on the ambient params; see ComposedSteps)."""
    return getattr(_PARAM_ENV, "params", None) is not None


@contextmanager
def param_scope(params):
    """Bind the lifted-literal parameter vectors for the duration of a
    trace (the fused program wraps its whole body in this; on a jit
    cache HIT the scope is never consulted — the compiled program
    reads the operand directly)."""
    prev = getattr(_PARAM_ENV, "params", None)
    _PARAM_ENV.params = params
    try:
        yield
    finally:
        _PARAM_ENV.params = prev


@dataclass(frozen=True, eq=False)
class LiftedLit(Expr):
    """A literal lifted to ``params[lane][slot]``: structurally equal
    across plans regardless of the VALUE, which rides in the dynamic
    parameter operand."""

    slot: int
    lane: str  # "i" (int64) | "f" (float64)

    def eval(self, chunk: DataChunk) -> EvalResult:
        params = getattr(_PARAM_ENV, "params", None)
        if params is None:
            raise RuntimeError(
                "LiftedLit evaluated outside a param_scope (lifted "
                "plans only run inside the fused barrier program)"
            )
        return jnp.full(chunk.capacity, params[self.lane][self.slot]), None


def lift_literals(value, ints: list, floats: list):
    """Rebuild an Expr-bearing structure with numeric Lits replaced by
    LiftedLit slots, appending the values to ``ints``/``floats`` in
    traversal order (the order is part of the structure, so equal
    shapes assign equal slots). Non-numeric literals (None/str/bool)
    stay baked — they steer trace-time control flow."""
    import dataclasses as _dc

    import numpy as _np

    def walk(v):
        if isinstance(v, LiftedLit):
            return v  # idempotent
        if isinstance(v, Lit):
            x = v.value
            if isinstance(x, bool) or isinstance(x, _np.bool_):
                return v
            if isinstance(x, (int, _np.integer)):
                ints.append(int(x))
                return LiftedLit(len(ints) - 1, "i")
            if isinstance(x, (float, _np.floating)):
                floats.append(float(x))
                return LiftedLit(len(floats) - 1, "f")
            return v
        if isinstance(v, Expr) and _dc.is_dataclass(v):
            return type(v)(
                *(walk(getattr(v, f.name)) for f in _dc.fields(v))
            )
        if isinstance(v, (tuple, list)):
            return tuple(walk(x) for x in v)
        if isinstance(v, dict):
            return {k: walk(x) for k, x in v.items()}
        return v

    return walk(value)


@dataclass(frozen=True, eq=False)
class AssumeNotNull(Expr):
    """Drop the NULL lane. The planner inserts this only AFTER a
    NULL-filter on the column: the surviving rows are provably
    non-null, so stripping the lane is semantics-preserving (null-
    lane-free consumers like the dedup keys accept the column)."""

    inner: Expr

    def eval(self, chunk: DataChunk) -> EvalResult:
        v, _ = self.inner.eval(chunk)
        return v, None


@dataclass(frozen=True, eq=False)
class Cast(Expr):
    """Device dtype cast (CAST(x AS t) on fixed-width lanes; logical-
    type casts — dictionary/decimal rescale — happen at the host
    edges, sql/typing.py)."""

    inner: Expr
    dtype: object  # numpy/jnp dtype

    def eval(self, chunk: DataChunk) -> EvalResult:
        v, n = self.inner.eval(chunk)
        return v.astype(self.dtype), n


_BIN_FNS: dict[str, Callable] = {
    "+": jnp.add,
    "-": jnp.subtract,
    "*": jnp.multiply,
    "//": jnp.floor_divide,
    "/": jnp.true_divide,  # float division (agg finishing: avg/var)
    "%": jnp.remainder,
    "==": jnp.equal,
    "!=": jnp.not_equal,
    "<": jnp.less,
    "<=": jnp.less_equal,
    ">": jnp.greater,
    ">=": jnp.greater_equal,
}


@dataclass(frozen=True, eq=False)
class BinOp(Expr):
    op: str
    left: Expr
    right: Expr

    def eval(self, chunk: DataChunk) -> EvalResult:
        lv, ln = self.left.eval(chunk)
        rv, rn = self.right.eval(chunk)
        nulls = _null_or(ln, rn)
        if self.op in ("//", "%", "/"):
            # guard div-by-zero on padding/NULL lanes; SQL raises on a
            # *visible* non-null zero divisor — the host checks that via
            # Filter/Project error lanes later; here we make it NULL so
            # no trap fires inside jit (non-strict eval, reference
            # src/expr/core/src/expr/non_strict.rs turns errors to NULL)
            zero_div = rv == 0
            nulls = _null_or(nulls, zero_div)
            rv = jnp.where(zero_div, jnp.ones((), rv.dtype), rv)
        return _BIN_FNS[self.op](lv, rv), nulls


@dataclass(frozen=True, eq=False)
class And(Expr):
    left: Expr
    right: Expr

    def eval(self, chunk: DataChunk) -> EvalResult:
        lv, ln = self.left.eval(chunk)
        rv, rn = self.right.eval(chunk)
        lv = lv.astype(jnp.bool_)
        rv = rv.astype(jnp.bool_)
        val = lv & rv
        if ln is None and rn is None:
            return val, None
        # SQL 3VL: NULL unless one side is a definite FALSE
        l_def_false = (~lv) & ~(ln if ln is not None else jnp.zeros_like(lv))
        r_def_false = (~rv) & ~(rn if rn is not None else jnp.zeros_like(rv))
        any_null = _null_or(ln, rn)
        nulls = any_null & ~l_def_false & ~r_def_false
        return val & ~nulls, nulls


@dataclass(frozen=True, eq=False)
class Or(Expr):
    left: Expr
    right: Expr

    def eval(self, chunk: DataChunk) -> EvalResult:
        lv, ln = self.left.eval(chunk)
        rv, rn = self.right.eval(chunk)
        lv = lv.astype(jnp.bool_)
        rv = rv.astype(jnp.bool_)
        val = lv | rv
        if ln is None and rn is None:
            return val, None
        l_def_true = lv & ~(ln if ln is not None else jnp.zeros_like(lv))
        r_def_true = rv & ~(rn if rn is not None else jnp.zeros_like(rv))
        any_null = _null_or(ln, rn)
        nulls = any_null & ~l_def_true & ~r_def_true
        return (val | l_def_true | r_def_true) & ~nulls, nulls


@dataclass(frozen=True, eq=False)
class Not(Expr):
    inner: Expr

    def eval(self, chunk: DataChunk) -> EvalResult:
        v, n = self.inner.eval(chunk)
        return ~v.astype(jnp.bool_), n


@dataclass(frozen=True, eq=False)
class IsNull(Expr):
    inner: Expr
    negate: bool = False

    def eval(self, chunk: DataChunk) -> EvalResult:
        _, n = self.inner.eval(chunk)
        isnull = n if n is not None else jnp.zeros(chunk.capacity, jnp.bool_)
        return (~isnull if self.negate else isnull), None


@dataclass(frozen=True, eq=False)
class Between(Expr):
    """lo <= v <= hi (inclusive, SQL BETWEEN)."""

    inner: Expr
    lo: Expr
    hi: Expr

    def eval(self, chunk: DataChunk) -> EvalResult:
        v, n = self.inner.eval(chunk)
        lo, ln = self.lo.eval(chunk)
        hi, hn = self.hi.eval(chunk)
        return (v >= lo) & (v <= hi), _null_or(n, _null_or(ln, hn))


@dataclass(frozen=True, eq=False)
class InList(Expr):
    inner: Expr
    values: Tuple

    def eval(self, chunk: DataChunk) -> EvalResult:
        v, n = self.inner.eval(chunk)
        hit = jnp.zeros(chunk.capacity, jnp.bool_)
        for item in self.values:
            hit = hit | (v == item)
        return hit, n


@dataclass(frozen=True, eq=False)
class Case(Expr):
    """CASE WHEN cond THEN val ... ELSE default END."""

    branches: Tuple[Tuple[Expr, Expr], ...]
    default: Expr

    def eval(self, chunk: DataChunk) -> EvalResult:
        evaluated = [
            (cond.eval(chunk), out.eval(chunk)) for cond, out in self.branches
        ]
        val, nulls = self.default.eval(chunk)
        # SQL CASE result type is promoted across ALL branches and the
        # default — coercing to the default's dtype would silently
        # truncate wider branch values (code-review r2)
        rdtype = jnp.result_type(val, *(ov for _, (ov, _) in evaluated))
        val = val.astype(rdtype)
        # evaluate in reverse so earlier branches win via jnp.where
        for (cv, cn), (ov, on) in reversed(evaluated):
            cv = cv.astype(jnp.bool_)
            if cn is not None:
                cv = cv & ~cn  # NULL condition does not fire a branch
            val = jnp.where(cv, ov.astype(rdtype), val)
            if nulls is not None or on is not None:
                base = nulls if nulls is not None else jnp.zeros_like(cv)
                bn = on if on is not None else jnp.zeros_like(cv)
                nulls = jnp.where(cv, bn, base)
        return val, nulls


@dataclass(frozen=True, eq=False)
class TumbleStart(Expr):
    """Tumbling-window bucket start: (ts // size) * size.

    Reference: the tumble() table function lowered into projections by
    the frontend (src/frontend/src/optimizer — window TVFs); Nexmark q7
    groups by tumble(date_time, 10s).
    """

    ts: Expr
    size_ms: int

    def eval(self, chunk: DataChunk) -> EvalResult:
        v, n = self.ts.eval(chunk)
        return (v // self.size_ms) * self.size_ms, n
