"""Expression mini-framework (vectorized, jit-composable).

Reference role: src/expr/core/src/expr/ — the ``Expression`` trait whose
impls evaluate over a whole ``DataChunk`` at once, plus the non-strict
NULL semantics baked into the #[function] codegen (src/expr/macro/).

TPU re-design: an expression is a tiny AST of pure-jnp node objects.
``Expr.eval(chunk) -> (values, nulls)`` returns a fixed-capacity value
lane and a bool NULL lane; everything composes under ``jax.jit`` with no
data-dependent shapes. Three-valued logic (AND/OR/NOT over NULL) follows
SQL exactly; arithmetic and comparison are NULL-strict.
"""

from risingwave_tpu.expr.expr import (
    And,
    Between,
    BinOp,
    Case,
    Cast,
    Col,
    Expr,
    InList,
    IsNull,
    Lit,
    Not,
    Or,
    TumbleStart,
    col,
    lit,
)

__all__ = [
    "Expr",
    "Col",
    "Lit",
    "BinOp",
    "Cast",
    "And",
    "Or",
    "Not",
    "IsNull",
    "Case",
    "Between",
    "InList",
    "TumbleStart",
    "col",
    "lit",
]
