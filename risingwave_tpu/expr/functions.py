"""Scalar function library + registry.

Reference: src/expr/impl/src/scalar/ (hundreds of #[function] kernels
registered into a global FUNCTION_REGISTRY the binder resolves against,
src/expr/core/src/sig/). Here each function is a pure jnp kernel over
(values, null_lane) pairs; the registry maps (name, arity) to it and
``Func`` nodes fuse into the same jitted expression trees as every
other node.

NULL policy mirrors the reference: strict by default (any NULL input
-> NULL output); COALESCE/NULLIF/IS-DISTINCT handle NULLs explicitly;
domain errors (div 0, sqrt(-x), log(0)) go NULL in non-strict stream
eval (src/expr/core/src/expr/non_strict.rs).

Temporal kernels treat TIMESTAMP as int64 ms since the Unix epoch and
use the classic civil-from-days integer algorithm, so EXTRACT /
DATE_TRUNC run vectorized on device — no host datetime objects.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.array.chunk import DataChunk
from risingwave_tpu.expr.expr import EvalResult, Expr, _null_or

# name -> (min_arity, max_arity, impl(values...) -> (value, extra_null))
_REGISTRY: Dict[str, Tuple[int, int, Callable]] = {}
# UDF name -> (out Field, arg Fields) for type inference at the edges
_UDF_SIGS: Dict[str, Tuple[object, Tuple[object, ...]]] = {}
# session-registered STRING BUILTINS: typed like UDFs but protected —
# CREATE FUNCTION cannot shadow them and DROP FUNCTION refuses
_PROTECTED: set = set()


def register(name, min_arity, max_arity=None):
    def deco(fn):
        _REGISTRY[name] = (min_arity, max_arity or min_arity, fn)
        return fn

    return deco


def lookup(name: str) -> Optional[Tuple[int, int, Callable]]:
    return _REGISTRY.get(name)


def registry_names():
    return sorted(_REGISTRY)


# -- numeric --------------------------------------------------------------
@register("abs", 1)
def _abs(v):
    return jnp.abs(v), None


@register("sign", 1)
def _sign(v):
    return jnp.sign(v), None


@register("ceil", 1)
def _ceil(v):
    return jnp.ceil(v) if jnp.issubdtype(v.dtype, jnp.floating) else v, None


@register("floor", 1)
def _floor(v):
    return jnp.floor(v) if jnp.issubdtype(v.dtype, jnp.floating) else v, None


@register("round", 1, 2)
def _round(v, digits=None):
    if not jnp.issubdtype(v.dtype, jnp.floating):
        return v, None
    if digits is None:
        return jnp.round(v), None
    scale = 10.0 ** digits
    return jnp.round(v * scale) / scale, None


@register("mod", 2)
def _mod(a, b):
    bad = b == 0
    safe = jnp.where(bad, jnp.ones((), b.dtype), b)
    return jnp.remainder(a, safe), bad


@register("pow", 2)
@register("power", 2)
def _pow(a, b):
    return jnp.power(a.astype(jnp.float64), b.astype(jnp.float64)), None


@register("sqrt", 1)
def _sqrt(v):
    f = v.astype(jnp.float64)
    bad = f < 0
    return jnp.sqrt(jnp.where(bad, 0.0, f)), bad


@register("exp", 1)
def _exp(v):
    return jnp.exp(v.astype(jnp.float64)), None


@register("ln", 1)
def _ln(v):
    f = v.astype(jnp.float64)
    bad = f <= 0
    return jnp.log(jnp.where(bad, 1.0, f)), bad


@register("log10", 1)
def _log10(v):
    f = v.astype(jnp.float64)
    bad = f <= 0
    return jnp.log10(jnp.where(bad, 1.0, f)), bad


@register("trunc", 1, 2)
def _trunc(v, digits=None):
    # preserve the input dtype (PG trunc(double) -> double); ints pass
    if not jnp.issubdtype(v.dtype, jnp.floating):
        return v, None
    if digits is None:
        return jnp.trunc(v), None
    scale = 10.0 ** digits
    return jnp.trunc(v * scale) / scale, None


@register("cbrt", 1)
def _cbrt(v):
    return jnp.cbrt(v.astype(jnp.float64)), None


@register("log2", 1)
def _log2(v):
    f = v.astype(jnp.float64)
    return jnp.log2(f), f <= 0


@register("log", 2)
def _log(b, x):
    fb, fx = b.astype(jnp.float64), x.astype(jnp.float64)
    bad = (fx <= 0) | (fb <= 0) | (fb == 1)
    return jnp.log(fx) / jnp.log(fb), bad


@register("sin", 1)
def _sin(v):
    return jnp.sin(v.astype(jnp.float64)), None


@register("cos", 1)
def _cos(v):
    return jnp.cos(v.astype(jnp.float64)), None


@register("tan", 1)
def _tan(v):
    return jnp.tan(v.astype(jnp.float64)), None


@register("cot", 1)
def _cot(v):
    f = v.astype(jnp.float64)
    return jnp.cos(f) / jnp.sin(f), None


@register("asin", 1)
def _asin(v):
    f = v.astype(jnp.float64)
    return jnp.arcsin(f), jnp.abs(f) > 1


@register("acos", 1)
def _acos(v):
    f = v.astype(jnp.float64)
    return jnp.arccos(f), jnp.abs(f) > 1


@register("atan", 1)
def _atan(v):
    return jnp.arctan(v.astype(jnp.float64)), None


@register("atan2", 2)
def _atan2(y, x):
    return (
        jnp.arctan2(y.astype(jnp.float64), x.astype(jnp.float64)),
        None,
    )


@register("sinh", 1)
def _sinh(v):
    return jnp.sinh(v.astype(jnp.float64)), None


@register("cosh", 1)
def _cosh(v):
    return jnp.cosh(v.astype(jnp.float64)), None


@register("tanh", 1)
def _tanh(v):
    return jnp.tanh(v.astype(jnp.float64)), None


@register("asinh", 1)
def _asinh(v):
    return jnp.arcsinh(v.astype(jnp.float64)), None


@register("acosh", 1)
def _acosh(v):
    f = v.astype(jnp.float64)
    return jnp.arccosh(jnp.where(f < 1, 1.0, f)), f < 1


@register("atanh", 1)
def _atanh(v):
    f = v.astype(jnp.float64)
    bad = jnp.abs(f) >= 1
    return jnp.arctanh(jnp.where(bad, 0.0, f)), bad


@register("factorial", 1)
def _factorial(v):
    # n! for n in [0, 20] fits int64; larger / negative -> NULL
    n = v.astype(jnp.int64)
    bad = (n < 0) | (n > 20)
    safe = jnp.clip(n, 0, 20)
    # cumulative product over a static table (device-friendly)
    table = jnp.cumprod(
        jnp.concatenate(
            [jnp.ones(1, jnp.int64), jnp.arange(1, 21, dtype=jnp.int64)]
        )
    )
    return table[safe], bad


@register("hypot", 2)
def _hypot(a, b):
    return (
        jnp.hypot(a.astype(jnp.float64), b.astype(jnp.float64)),
        None,
    )


@register("degrees", 1)
def _degrees(v):
    return jnp.degrees(v.astype(jnp.float64)), None


@register("radians", 1)
def _radians(v):
    return jnp.radians(v.astype(jnp.float64)), None


@register("gcd", 2)
def _gcd(a, b):
    return jnp.gcd(a.astype(jnp.int64), b.astype(jnp.int64)), None


@register("lcm", 2)
def _lcm(a, b):
    return jnp.lcm(a.astype(jnp.int64), b.astype(jnp.int64)), None


@register("bit_and", 2)
def _bit_and(a, b):
    return a.astype(jnp.int64) & b.astype(jnp.int64), None


@register("bit_or", 2)
def _bit_or(a, b):
    return a.astype(jnp.int64) | b.astype(jnp.int64), None


@register("bit_xor", 2)
def _bit_xor(a, b):
    return a.astype(jnp.int64) ^ b.astype(jnp.int64), None


@register("bit_not", 1)
def _bit_not(v):
    return ~v.astype(jnp.int64), None


@register("bit_shift_left", 2)
def _bshl(v, n):
    return v.astype(jnp.int64) << n.astype(jnp.int64), None


@register("bit_shift_right", 2)
def _bshr(v, n):
    return v.astype(jnp.int64) >> n.astype(jnp.int64), None


@register("greatest", 2, 8)
def _greatest(*vs):
    out = vs[0]
    for v in vs[1:]:
        out = jnp.maximum(out, v)
    return out, None


@register("least", 2, 8)
def _least(*vs):
    out = vs[0]
    for v in vs[1:]:
        out = jnp.minimum(out, v)
    return out, None


# -- temporal (int64 ms since epoch) ---------------------------------------
_MS_DAY = 86_400_000
_MS_HOUR = 3_600_000
_MS_MIN = 60_000
_MS_SEC = 1_000


def _civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day); the classic integer
    civil-calendar algorithm, fully vectorized."""
    z = days + 719_468
    era = jnp.floor_divide(jnp.where(z >= 0, z, z - 146_096), 146_097)
    doe = z - era * 146_097  # [0, 146096]
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460)
        + jnp.floor_divide(doe, 36_524)
        - jnp.floor_divide(doe, 146_096),
        365,
    )
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    return jnp.where(m <= 2, y + 1, y), m, d


def _days_from_civil(y, m, d):
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.floor_divide(jnp.where(y >= 0, y, y - 399), 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100) + doy
    return era * 146_097 + doe - 719_468


_EXTRACT_FIELDS = (
    "epoch", "millisecond", "second", "minute", "hour",
    "day", "month", "year", "dow", "doy",
)


def extract_field(field: str, ts: jnp.ndarray) -> jnp.ndarray:
    ts = ts.astype(jnp.int64)
    days = jnp.floor_divide(ts, _MS_DAY)
    ms_of_day = ts - days * _MS_DAY
    if field == "epoch":
        return jnp.floor_divide(ts, _MS_SEC)
    if field == "millisecond":
        return jnp.remainder(ms_of_day, _MS_SEC)
    if field == "second":
        return jnp.remainder(jnp.floor_divide(ms_of_day, _MS_SEC), 60)
    if field == "minute":
        return jnp.remainder(jnp.floor_divide(ms_of_day, _MS_MIN), 60)
    if field == "hour":
        return jnp.floor_divide(ms_of_day, _MS_HOUR)
    if field == "dow":  # 0 = Sunday (postgres)
        return jnp.remainder(days + 4, 7)
    y, m, d = _civil_from_days(days)
    if field == "year":
        return y
    if field == "month":
        return m
    if field == "day":
        return d
    if field == "doy":
        jan1 = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return days - jan1 + 1
    raise ValueError(f"unknown EXTRACT field {field!r}")


def date_trunc_field(field: str, ts: jnp.ndarray) -> jnp.ndarray:
    ts = ts.astype(jnp.int64)
    if field == "second":
        return (ts // _MS_SEC) * _MS_SEC
    if field == "minute":
        return (ts // _MS_MIN) * _MS_MIN
    if field == "hour":
        return (ts // _MS_HOUR) * _MS_HOUR
    if field == "day":
        return (ts // _MS_DAY) * _MS_DAY
    if field == "week":  # Monday start (postgres)
        days = jnp.floor_divide(ts, _MS_DAY)
        dow_mon = jnp.remainder(days + 3, 7)  # 0 = Monday
        return (days - dow_mon) * _MS_DAY
    days = jnp.floor_divide(ts, _MS_DAY)
    y, m, d = _civil_from_days(days)
    if field == "month":
        return _days_from_civil(y, m, jnp.ones_like(d)) * _MS_DAY
    if field == "year":
        return (
            _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d)) * _MS_DAY
        )
    raise ValueError(f"unknown date_trunc field {field!r}")


# -- expr nodes -------------------------------------------------------------
@dataclass(frozen=True, eq=False)
class Func(Expr):
    """Registry-dispatched scalar function, NULL-strict."""

    name: str
    args: Tuple[Expr, ...]

    def eval(self, chunk: DataChunk) -> EvalResult:
        entry = lookup(self.name)
        if entry is None:
            raise KeyError(f"unknown function {self.name!r}")
        lo, hi, impl = entry
        if not (lo <= len(self.args) <= hi):
            raise TypeError(
                f"{self.name}() takes {lo}..{hi} args, got {len(self.args)}"
            )
        vals, nulls = [], None
        for a in self.args:
            v, n = a.eval(chunk)
            vals.append(v)
            nulls = _null_or(nulls, n)
        out, extra = impl(*vals)
        return out, _null_or(nulls, extra)


@dataclass(frozen=True, eq=False)
class Extract(Expr):
    field: str
    ts: Expr

    def eval(self, chunk: DataChunk) -> EvalResult:
        v, n = self.ts.eval(chunk)
        return extract_field(self.field, v), n


@dataclass(frozen=True, eq=False)
class DateTrunc(Expr):
    field: str
    ts: Expr

    def eval(self, chunk: DataChunk) -> EvalResult:
        v, n = self.ts.eval(chunk)
        return date_trunc_field(self.field, v), n


@dataclass(frozen=True, eq=False)
class Coalesce(Expr):
    args: Tuple[Expr, ...]

    def eval(self, chunk: DataChunk) -> EvalResult:
        val, nulls = self.args[0].eval(chunk)
        for a in self.args[1:]:
            if nulls is None:
                break
            v, n = a.eval(chunk)
            rdtype = jnp.result_type(val, v)
            val = jnp.where(nulls, v.astype(rdtype), val.astype(rdtype))
            nulls = (
                nulls & n if n is not None else jnp.zeros_like(nulls)
            )
        return val, nulls


@dataclass(frozen=True, eq=False)
class NullIf(Expr):
    a: Expr
    b: Expr

    def eval(self, chunk: DataChunk) -> EvalResult:
        av, an = self.a.eval(chunk)
        bv, bn = self.b.eval(chunk)
        eq = av == bv
        if bn is not None:
            eq = eq & ~bn  # NULL never equals
        if an is not None:
            eq = eq & ~an
        return av, _null_or(an, eq)


# -- dictionary-backed string functions ------------------------------------
@dataclass(frozen=True, eq=False)
class StringFunc(Expr):
    """VARCHAR function over dictionary codes (array/dictionary.py):
    the host maps the (small) dictionary once — upper/lower yield a
    code->code table, length a code->int table — and the device applies
    it as one gather. Amortized O(dictionary), not O(rows)."""

    name: str  # upper | lower | length
    inner: Expr
    dictionary: object  # StringDictionary

    def _table(self):
        d = self.dictionary
        strings = [d.decode_one(i) for i in range(len(d))]
        if self.name == "length":
            return jnp.asarray(
                np.fromiter((len(s) for s in strings), np.int64,
                            count=len(strings))
            )
        fn = str.upper if self.name == "upper" else str.lower
        return jnp.asarray(d.encode([fn(s) for s in strings]))

    def eval(self, chunk: DataChunk) -> EvalResult:
        v, n = self.inner.eval(chunk)
        table = self._table()
        safe = jnp.clip(v, 0, table.shape[0] - 1)
        return table[safe], n


# -- user-defined functions ------------------------------------------------
# Reference: src/expr/impl/src/udf/python.rs (embedded python UDFs,
# batched over arrow arrays). TPU re-design: the UDF body runs host-side
# through jax.pure_callback, so Func nodes containing a UDF still trace
# into jitted expression programs — XLA suspends at the callback, ships
# the operand lanes to the host, and resumes with the result lane.
# Row-level exceptions become SQL NULL (the reference's non-strict
# error->NULL policy) via the extra-null lane.


def _udf_lane_in(field, v, strings):
    """Device-lane cell -> the python value a UDF body receives."""
    import json as _json
    from decimal import Decimal as _Dec

    from risingwave_tpu.types import DataType as _DT

    if field.dtype is _DT.VARCHAR:
        return strings.decode_one(int(v))
    if field.dtype is _DT.JSONB:
        return _json.loads(strings.decode_one(int(v)))
    if field.dtype is _DT.DECIMAL:
        return _Dec(int(v)).scaleb(-field.scale)
    return v


def _udf_lane_out(field, v, strings):
    """UDF return value -> the device-lane cell encoding."""
    import json as _json
    from decimal import Decimal as _Dec

    from risingwave_tpu.types import DataType as _DT

    if field.dtype is _DT.VARCHAR:
        return strings.encode_one(str(v))
    if field.dtype is _DT.JSONB:
        return strings.encode_one(
            _json.dumps(v, sort_keys=True, separators=(",", ":"))
        )
    if field.dtype is _DT.DECIMAL:
        # str(v) handles str returns (external UDFs cross DECIMAL as
        # str) and floats alike; repr(str) would produce "'1.23'"
        d = v if isinstance(v, _Dec) else _Dec(str(v))
        return int(d.scaleb(field.scale).to_integral_value())
    return v


def _check_udf_registrable(
    lname: str, out_field, arg_fields, strings, allow_builtin=False
):
    """Shared registration guards: builtins are not replaceable
    (unless the session itself registers protected string builtins)
    and dictionary-typed signatures need the session dictionary."""
    from risingwave_tpu.types import DataType as _DT

    if not allow_builtin and (
        (lname in _REGISTRY and lname not in _UDF_SIGS)
        or lname in _PROTECTED
    ):
        raise ValueError(
            f"{lname!r} is a builtin function and cannot be replaced"
        )
    dict_types = (_DT.VARCHAR, _DT.JSONB)
    if strings is None and (
        out_field.dtype in dict_types
        or any(f.dtype in dict_types for f in arg_fields)
    ):
        raise ValueError(
            "VARCHAR/JSONB UDF signatures need the session dictionary"
        )


def register_py_udf(
    name: str,
    fn: Callable,
    out_field,
    arg_fields,
    strings=None,
    protected: bool = False,
) -> None:
    """Register a scalar python UDF callable under ``name`` (lowercased
    — SQL identifiers fold to lower case in the lexer).

    ``fn`` is row-scalar. ``out_field``/``arg_fields`` are logical
    Fields: VARCHAR/JSONB args decode dictionary codes to python
    strings/objects before the call and the return value encodes back;
    DECIMAL crosses as Decimal. Vectorization happens in the callback;
    error rows yield SQL NULL.

    The registry is process-global (the reference keeps functions in a
    cluster catalog): a UDF binds the dictionary of the session that
    created it, so VARCHAR/JSONB UDFs are only meaningful in that
    session — a second in-process session must CREATE its own."""
    import json as _json
    from decimal import Decimal as _Dec

    from risingwave_tpu.types import DataType as _DT

    if not arg_fields:
        raise NotImplementedError(
            "zero-argument UDFs are not supported (use a literal)"
        )
    lname = name.lower()
    _check_udf_registrable(
        lname, out_field, arg_fields, strings, allow_builtin=protected
    )
    out_np = np.dtype(out_field.dtype.device_dtype)

    def _in(field, v):
        return _udf_lane_in(field, v, strings)

    def _out(v):
        return _udf_lane_out(out_field, v, strings)

    def impl(*values):
        import jax

        n = values[0].shape[0]

        def host(*arrs):
            out = np.zeros(n, out_np)
            err = np.zeros(n, np.bool_)
            cols = [np.asarray(a) for a in arrs]
            for i in range(n):
                try:
                    out[i] = _out(
                        fn(
                            *(
                                _in(f, c[i].item())
                                for f, c in zip(arg_fields, cols)
                            )
                        )
                    )
                except Exception:  # noqa: BLE001 — row error -> NULL
                    err[i] = True
            return out, err

        val, err = jax.pure_callback(
            host,
            (
                jax.ShapeDtypeStruct((n,), out_np),
                jax.ShapeDtypeStruct((n,), np.bool_),
            ),
            *values,
        )
        return val, err

    arity = len(arg_fields)
    _REGISTRY[name.lower()] = (arity, arity, impl)
    _UDF_SIGS[name.lower()] = (out_field, tuple(arg_fields))
    if protected:
        _PROTECTED.add(name.lower())


def register_external_udf(
    name: str,
    address: str,
    out_field,
    arg_fields,
    strings=None,
    timeout: float = 5.0,
    retries: int = 2,
) -> None:
    """Register a scalar UDF served by an OUT-OF-PROCESS UDF server
    (risingwave_tpu/udf_server.py; reference: udf/external.rs — the
    flight-service client). One batched RPC per chunk through
    jax.pure_callback; lane coercions match the embedded runtime
    (VARCHAR/JSONB decode to python values, DECIMAL crosses as str).
    Row errors and NULL args yield SQL NULL; an unreachable service
    raises (a missing UDF service is a query error, not silent NULLs).
    """
    from risingwave_tpu.types import DataType as _DT
    from risingwave_tpu.udf_server import call_external

    if not arg_fields:
        raise NotImplementedError("zero-argument UDFs are not supported")
    lname = name.lower()
    _check_udf_registrable(lname, out_field, arg_fields, strings)
    out_np = np.dtype(out_field.dtype.device_dtype)

    def _wire_in(field, v):
        # JSON-safe request cell; an undecodable cell (e.g. the empty-
        # string fill of a NULL JSONB lane) crosses as None -> the
        # server returns row NULL, matching the embedded runtime's
        # bad-cell-becomes-NULL policy
        try:
            x = _udf_lane_in(field, v, strings)
        except Exception:
            return None
        if field.dtype is _DT.DECIMAL:
            return str(x)
        return x

    def impl(*values):
        import jax

        n = values[0].shape[0]

        def host(*arrs):
            cols = [
                [_wire_in(f, c) for c in np.asarray(a).tolist()]
                for f, a in zip(arg_fields, arrs)
            ]
            vals, nls = call_external(
                address, lname, cols, timeout=timeout, retries=retries
            )
            out = np.zeros(n, out_np)
            err = np.zeros(n, np.bool_)
            for i in range(n):
                if nls[i] or vals[i] is None:
                    err[i] = True
                    continue
                try:
                    out[i] = _udf_lane_out(out_field, vals[i], strings)
                except Exception:
                    err[i] = True
            return out, err

        return jax.pure_callback(
            host,
            (
                jax.ShapeDtypeStruct((n,), out_np),
                jax.ShapeDtypeStruct((n,), np.bool_),
            ),
            *values,
        )

    arity = len(arg_fields)
    _REGISTRY[lname] = (arity, arity, impl)
    _UDF_SIGS[lname] = (out_field, tuple(arg_fields))


def drop_function(name: str) -> bool:
    """Drop a UDF; builtins (kernel or protected string builtins) are
    not droppable."""
    if name.lower() not in _UDF_SIGS or name.lower() in _PROTECTED:
        return False
    _UDF_SIGS.pop(name.lower(), None)
    return _REGISTRY.pop(name.lower(), None) is not None


def is_protected(name: str) -> bool:
    return name.lower() in _PROTECTED


def udf_signature(name: str):
    """(out_field, arg_fields) | None — lets the result edge decode
    UDF outputs (dictionary codes / scaled decimals) by logical type."""
    return _UDF_SIGS.get(name.lower())
