"""End-to-end freshness tracking + backpressure attribution.

Reference: TiLT's time-centric view (PAPERS.md) — the latency a *user*
experiences is source-ingest -> visible-snapshot, not the processing
cost of any one stage — and the reference's `rw_ddl_progress` /
`rw_fragments` introspection surfaces, which serve system state off the
same versioned store the queries read.

The tracker is the host-side spine of ISSUE 16's tentpole: every
barrier, `runtime._end_trace` (after `arrangements.publish` makes the
epoch's snapshot readable) folds three wall-clock deltas per MV into
windowed histograms and a latest-row table:

- ``mv_freshness_ms{mv}``      barrier-open -> snapshot-visible (the
                               commit->visible SLO the BASELINE north
                               star is written in);
- ``source_to_visible_ms{mv}`` first ingest of the epoch -> visible;
- ``event_time_lag_ms{mv}``    wall clock vs the fragment's
                               low-watermark frontier (event time).

Everything here is host timestamps and dict updates: ZERO added device
dispatches, and the accumulated host cost is self-measured
(``host_ms``) so perf_gate --freshness can hold the <1% -of-steady-
barrier budget the blackbox ring already lives under.

``attribute_backpressure`` is the companion verdict: per-fragment
dispatch walls (EpochTrace.fragment_ms) + per-channel depth and
oldest-pending-epoch AGE (PermitChannel.oldest_pending) folded into one
``backpressure_fragment`` name per barrier — a slow barrier names the
actor that caused it instead of a number.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from risingwave_tpu.metrics import REGISTRY


class FreshnessTracker:
    """Latest-row + bounded-history store behind ``rw_mv_freshness``,
    the dashboard's freshness table, and dump_stalls."""

    HISTORY = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self._latest: Dict[str, dict] = {}
        self._history: deque = deque(maxlen=self.HISTORY)
        self.host_ms = 0.0  # self-measured tracking cost (perf_gate)

    def observe(
        self,
        mv: str,
        epoch: int,
        checkpoint: bool = False,
        commit_to_visible_ms: Optional[float] = None,
        source_to_visible_ms: Optional[float] = None,
        event_time_lag_ms: Optional[float] = None,
    ) -> dict:
        t0 = time.perf_counter()
        row = {
            "mv": mv,
            "epoch": int(epoch),
            "checkpoint": bool(checkpoint),
            "commit_to_visible_ms": commit_to_visible_ms,
            "source_to_visible_ms": source_to_visible_ms,
            "event_time_lag_ms": event_time_lag_ms,
            "visible_at": time.time(),
        }
        if commit_to_visible_ms is not None:
            REGISTRY.histogram("mv_freshness_ms").observe(
                commit_to_visible_ms, mv=mv
            )
        if source_to_visible_ms is not None:
            REGISTRY.histogram("source_to_visible_ms").observe(
                source_to_visible_ms, mv=mv
            )
        if event_time_lag_ms is not None:
            REGISTRY.histogram("event_time_lag_ms").observe(
                event_time_lag_ms, mv=mv
            )
            REGISTRY.gauge("event_time_lag_ms_last").set(
                event_time_lag_ms, mv=mv
            )
        with self._lock:
            prev = self._latest.get(mv)
            row["barriers"] = (prev["barriers"] + 1) if prev else 1
            self._latest[mv] = row
            self._history.append(row)
        self.host_ms += (time.perf_counter() - t0) * 1e3
        return row

    def snapshot(self) -> List[dict]:
        """Latest row per MV, sorted by name (rw_mv_freshness scan)."""
        with self._lock:
            return [dict(self._latest[m]) for m in sorted(self._latest)]

    def history(self, limit: int = 256) -> List[dict]:
        with self._lock:
            rows = list(self._history)
        return rows[-limit:]

    def drop(self, mv: str) -> None:
        with self._lock:
            self._latest.pop(mv, None)

    def reset(self) -> None:
        with self._lock:
            self._latest.clear()
            self._history.clear()
        self.host_ms = 0.0


# the process-default tracker (like metrics.REGISTRY / event_log.EVENT_LOG)
FRESHNESS = FreshnessTracker()


def attribute_backpressure(runtime, trace) -> dict:
    """Fold the barrier's per-fragment dispatch walls + channel
    depth/oldest-pending-age into one bottleneck verdict.

    Returns ``{"fragment": name|None, "ms": float, "detail": {...}}``
    and records ``backpressure_ms{fragment}`` + per-fragment channel
    gauges. Score = fragment dispatch wall + oldest pending age across
    its input channels: a fragment is the bottleneck either because its
    own dispatch dominated the barrier or because work has been sitting
    unconsumed in front of it since an old epoch.
    """
    t0 = time.perf_counter()
    detail: Dict[str, dict] = {}
    for name, p in getattr(runtime, "fragments", {}).items():
        ent = {
            "dispatch_ms": round(
                getattr(trace, "fragment_ms", {}).get(name, 0.0), 3
            )
        }
        g = getattr(p, "graph", None)
        if g is not None:
            depth = 0
            oldest_age_ms = 0.0
            oldest_epoch = None
            try:
                for a in g.actors:
                    for _port, ch in a.inputs:
                        op = ch.oldest_pending()
                        if op is None:
                            continue
                        depth += len(ch)
                        age = op["age_ms"]
                        if age > oldest_age_ms:
                            oldest_age_ms = age
                            oldest_epoch = op.get("epoch")
            except Exception:
                pass  # attribution never faults a barrier
            ent["channel_depth"] = depth
            ent["oldest_age_ms"] = round(oldest_age_ms, 3)
            if oldest_epoch is not None:
                ent["oldest_epoch"] = oldest_epoch
            REGISTRY.gauge("channel_depth").set(float(depth), fragment=name)
        detail[name] = ent

    def score(e: dict) -> float:
        return e.get("dispatch_ms", 0.0) + e.get("oldest_age_ms", 0.0)

    frag = max(detail, key=lambda n: score(detail[n])) if detail else None
    ms = score(detail[frag]) if frag else 0.0
    if frag is not None:
        REGISTRY.histogram("backpressure_ms").observe(ms, fragment=frag)
    FRESHNESS.host_ms += (time.perf_counter() - t0) * 1e3
    return {"fragment": frag, "ms": round(ms, 3), "detail": detail}
