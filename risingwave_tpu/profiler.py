"""Dispatch-wall profiler — per-executor flame attribution for the
host dispatch path.

Reference: the reference gets per-executor latency/throughput metrics
from ``StreamingMetrics`` (executor/monitor/streaming_stats.rs) and
per-await-point attribution from await-tree + `tracing`; Grafana turns
those into the flame view an operator reads when an actor is slow.
Here the analogous question is sharper: BENCH stage data shows the
per-barrier ``dispatch`` stage at ~319ms p99 while ``device_step`` is
0.24ms — the host-side Python walk dominates and the device idles.
This module decomposes that wall:

- ``PROFILER.run(ex, phase, fn, *args)`` times every executor call in
  the dispatch walk into ``executor_ms{executor,fragment,phase}``
  (host-python time) and — in fence mode — ``executor_device_wait_ms``
  (explicit ``jax.block_until_ready`` on the call's outputs, so device
  wait is attributed to the executor that staged it, not smeared into
  the barrier fence).
- A kernel interposer wraps every module-level jitted kernel in
  ``risingwave_tpu.*`` with a counting proxy while profiling:
  ``device_dispatches_total{executor}`` / ``{kernel}`` count one
  Python-level jitted call ≈ one XLA program dispatch — the
  per-operator dispatch tax the fragment-fusion work (ROADMAP item 1)
  must drive toward one-per-barrier.
- Host<->device transfer accounting: ``jax.device_get``/``device_put``
  are wrapped to count ``host_device_transfers_total{direction}``
  ("log+count": implicit transfers stay visible via the armed
  ``jax.transfer_guard``; explicit ones are counted here).
- ``jax.profiler.trace`` capture windows: on-demand
  (``start_capture``) and auto-triggered when a barrier exceeds
  ``slow_barrier_ms`` — the next barrier is captured and a
  ``PROFILE_*`` JSON artifact (executor breakdown + dispatch/transfer
  counters + device forensics) is emitted. Capture windows are
  tracked so recovery can close them (``abort_captures``) — a partial
  recovery must never leave an orphaned profiler session holding the
  device.

Hot-path contract: everything above is gated on ONE ``PROFILER.enabled``
attribute check — profile-mode-off overhead is a single branch per
call site (<1% of a steady-state barrier, asserted in
tests/test_profiler.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from risingwave_tpu.metrics import REGISTRY

__all__ = ["PROFILER", "DispatchProfiler", "device_forensics"]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


# ---------------------------------------------------------------------------
# kernel interposer — count Python-level jitted-kernel dispatches
# ---------------------------------------------------------------------------


class _KernelProxy:
    """Counting wrapper around one module-level jitted kernel. Calls
    delegate to the wrapped function unchanged; attribute access
    (``_cache_size``, ``lower`` — RecompileWatch / check_donation)
    passes through, so holders of a proxy see the original surface."""

    __slots__ = ("_fn", "_kernel", "_prof")

    def __init__(self, fn, kernel: str, prof: "DispatchProfiler"):
        self._fn = fn
        self._kernel = kernel
        self._prof = prof

    def __call__(self, *args, **kwargs):
        self._prof._count_dispatch(self._kernel)
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)


def _is_jitted(obj) -> bool:
    """A module-level jit-compiled callable: the PjitFunction surface
    RecompileWatch already relies on (``_cache_size`` + ``lower``)."""
    return (
        callable(obj)
        and not isinstance(obj, _KernelProxy)
        and hasattr(obj, "_cache_size")
        and hasattr(obj, "lower")
    )


# ---------------------------------------------------------------------------
# the profiler
# ---------------------------------------------------------------------------


class DispatchProfiler:
    """Process-wide dispatch-wall profiler. Off by default; the hot
    paths check ``enabled`` once and skip everything below."""

    def __init__(self):
        self.enabled = False
        # fence mode: block_until_ready after each profiled call so
        # device wait is attributed per executor (profiling semantics —
        # values identical, async overlap serialized)
        self.fence = True
        # slow-barrier auto-capture threshold (ms); 0/None = off
        self.slow_barrier_ms: Optional[float] = None
        self.capture_dir: Optional[str] = None
        # arm jax.profiler.trace inside capture windows (heavy; the
        # JSON artifact is always written regardless)
        self.jax_trace = False
        self._tls = threading.local()
        self._lock = threading.Lock()
        # interposer bookkeeping: [(module, attr, original)]
        self._patched: List[Tuple[object, str, object]] = []
        self._jax_patched: List[Tuple[str, object]] = []
        # open jax.profiler/artifact capture windows (orphan audit
        # surface: recovery must leave this empty)
        self.active_captures: List[Dict] = []
        self._capture_armed = False
        # slow-barrier AUTO-captures attempted (manual captures do not
        # consume this budget; attempts count even when the artifact
        # write fails, so an unwritable dir cannot un-bound the loop)
        self._auto_captures = 0
        self.max_auto_captures = 3

    # -- lifecycle --------------------------------------------------------
    def enable(
        self,
        fence: bool = True,
        slow_barrier_ms: Optional[float] = None,
        capture_dir: Optional[str] = None,
        jax_trace: Optional[bool] = None,
    ) -> "DispatchProfiler":
        with self._lock:
            self.fence = fence
            if slow_barrier_ms is not None:
                self.slow_barrier_ms = slow_barrier_ms
            if capture_dir is not None:
                self.capture_dir = capture_dir
            if jax_trace is not None:
                self.jax_trace = jax_trace
            if not self.enabled:
                self._install_interposers()
                self.enabled = True
        return self

    def disable(self) -> None:
        with self._lock:
            if not self.enabled:
                return
            self.enabled = False
            self._remove_interposers()
        self.abort_captures()

    def reset(self) -> None:
        """Zero the profiler's metric surfaces (a bench child resets
        between queries so each query's breakdown stands alone)."""
        for h in ("executor_ms", "executor_device_wait_ms"):
            REGISTRY.histograms.pop(h, None)
        for c in (
            "device_dispatches_total",
            "device_dispatch_kernels_total",
            "host_device_transfers_total",
        ):
            REGISTRY.counters.pop(c, None)

    @classmethod
    def from_env(cls) -> "DispatchProfiler":
        """Honor RW_PROFILE / RW_PROFILE_FENCE / RW_PROFILE_SLOW_MS /
        RW_PROFILE_DIR / RW_PROFILE_JAX_TRACE on the process singleton.
        An EXPLICIT RW_PROFILE=0 disables even a config-enabled
        profiler — the env knob wins in both directions (the operator's
        no-restart escape hatch)."""
        raw = os.environ.get("RW_PROFILE")
        val = (raw or "0").strip().lower()
        if val in ("1", "on", "true"):
            PROFILER.enable(
                fence=os.environ.get("RW_PROFILE_FENCE", "1") != "0",
                slow_barrier_ms=_env_float("RW_PROFILE_SLOW_MS", 0) or None,
                capture_dir=os.environ.get("RW_PROFILE_DIR") or None,
                jax_trace=os.environ.get("RW_PROFILE_JAX_TRACE") == "1",
            )
        elif raw is not None and val in ("0", "off", "false"):
            PROFILER.disable()
        return PROFILER

    def configure(self, cfg) -> "DispatchProfiler":
        """Apply a config.ProfilerConfig (TOML ``[profiler]``); env
        knobs (from_env) win afterwards — the no-restart escape hatch."""
        if getattr(cfg, "enabled", False):
            self.enable(
                fence=cfg.fence,
                slow_barrier_ms=cfg.slow_barrier_capture_ms or None,
                capture_dir=cfg.capture_dir or None,
                jax_trace=cfg.jax_trace,
            )
        return self.from_env()

    # -- interposers ------------------------------------------------------
    def _install_interposers(self) -> None:
        import sys

        import jax

        for name, mod in list(sys.modules.items()):
            if not name.startswith("risingwave_tpu") or mod is None:
                continue
            for attr in list(vars(mod)):
                fn = vars(mod)[attr]
                if _is_jitted(fn):
                    setattr(mod, attr, _KernelProxy(fn, attr, self))
                    self._patched.append((mod, attr, fn))
        # explicit-transfer accounting (device_get/put call sites use
        # `jax.device_get(...)` attribute lookups, so a module-attr
        # wrapper intercepts them; implicit transfers are the armed
        # transfer_guard's job)
        prof = self

        def _get(x, _orig=jax.device_get):
            prof._count_transfer("d2h")
            return _orig(x)

        def _put(x, *a, _orig=jax.device_put, **kw):
            prof._count_transfer("h2d")
            return _orig(x, *a, **kw)

        self._jax_patched = [
            ("device_get", jax.device_get),
            ("device_put", jax.device_put),
        ]
        jax.device_get = _get
        jax.device_put = _put

    def _remove_interposers(self) -> None:
        import jax

        for mod, attr, fn in self._patched:
            # only restore if our proxy is still in place (a reload or
            # another patcher may have replaced it since)
            if isinstance(vars(mod).get(attr), _KernelProxy):
                setattr(mod, attr, fn)
        self._patched = []
        for attr, fn in self._jax_patched:
            setattr(jax, attr, fn)
        self._jax_patched = []

    # -- counters ---------------------------------------------------------
    def _count_dispatch(self, kernel: str) -> None:
        ex = getattr(self._tls, "executor", None) or "-"
        REGISTRY.counter("device_dispatches_total").inc(executor=ex)
        REGISTRY.counter("device_dispatch_kernels_total").inc(kernel=kernel)

    def _count_transfer(self, direction: str) -> None:
        REGISTRY.counter("host_device_transfers_total").inc(
            direction=direction
        )

    @staticmethod
    def _counter_snapshot(name: str) -> Dict:
        """Copy a counter's label->value map under the registry lock —
        forensic readers (stall dumps from watchdog threads) must not
        race a hot-path label insertion mid-iteration."""
        c = REGISTRY.counters.get(name)
        if c is None:
            return {}
        with REGISTRY._lock:
            return dict(c._values)

    def total_dispatches(self) -> float:
        return sum(self._counter_snapshot("device_dispatches_total").values())

    def dispatch_counts(self) -> Dict[str, float]:
        """{executor: dispatches} since enable/reset."""
        return {
            dict(k).get("executor", "-"): v
            for k, v in self._counter_snapshot(
                "device_dispatches_total"
            ).items()
        }

    def kernel_counts(self) -> Dict[str, float]:
        return {
            dict(k).get("kernel", "-"): v
            for k, v in self._counter_snapshot(
                "device_dispatch_kernels_total"
            ).items()
        }

    def transfer_counts(self) -> Dict[str, float]:
        out = {"d2h": 0.0, "h2d": 0.0}
        for k, v in self._counter_snapshot(
            "host_device_transfers_total"
        ).items():
            out[dict(k).get("direction", "-")] = v
        return out

    # -- the hot-path hook ------------------------------------------------
    def run(self, ex, phase: str, fn, *args, **kwargs):
        """Time one executor call. ``phase``: "apply" (data path),
        "flush" (on_barrier) — an apply inside a barrier window is
        relabeled "barrier_apply" so the dispatch-stage decomposition
        separates flush-propagation from ingest-side applies."""
        tls = self._tls
        if phase == "apply" and getattr(tls, "in_barrier", False):
            phase = "barrier_apply"
        name = type(ex).__name__
        frag = getattr(tls, "fragment", None) or "-"
        prev = getattr(tls, "executor", None)
        tls.executor = name
        t0 = time.perf_counter()
        try:
            out = fn(*args, **kwargs)
        finally:
            tls.executor = prev
        t1 = time.perf_counter()
        REGISTRY.histogram("executor_ms").observe(
            (t1 - t0) * 1e3, executor=name, fragment=frag, phase=phase
        )
        if self.fence:
            self._fence_outputs(out)
            wait_ms = (time.perf_counter() - t1) * 1e3
            REGISTRY.histogram("executor_device_wait_ms").observe(
                wait_ms, executor=name, fragment=frag, phase=phase
            )
        return out

    @staticmethod
    def _fence_outputs(out) -> None:
        """block_until_ready on whatever device values the call
        produced (chunk columns/valid lanes). Never raises — a fence
        failure must not change execution."""
        import jax

        try:
            leaves = []
            for c in out if isinstance(out, (list, tuple)) else (out,):
                cols = getattr(c, "columns", None)
                if cols:
                    leaves.extend(cols.values())
                v = getattr(c, "valid", None)
                if v is not None:
                    leaves.append(v)
            if leaves:
                jax.block_until_ready(leaves)
        except Exception:
            pass

    @contextmanager
    def attribute(self, label: str):
        """Attribute device dispatches inside the block to ``label``
        instead of the enclosing executor class — the fused per-barrier
        step reports as ONE ``device_dispatches_total{executor=
        "fused:<fragment>"}`` entry, so dispatches/barrier stays
        auditable after fusion collapses a chain into one program."""
        tls = self._tls
        prev = getattr(tls, "executor", None)
        tls.executor = label
        try:
            yield
        finally:
            tls.executor = prev

    def record_device_wait(
        self, ex, ms: float, phase: str = "finish", fragment: str = None
    ) -> None:
        """Attribute an explicit barrier-fence wait (staged-scalar
        materialization in ``Executor.finish_barrier``) to its executor."""
        REGISTRY.histogram("executor_device_wait_ms").observe(
            ms,
            executor=type(ex).__name__,
            fragment=fragment or getattr(self._tls, "fragment", None) or "-",
            phase=phase,
        )

    @contextmanager
    def barrier_window(self, fragment: Optional[str] = None):
        """Mark the enclosed calls as barrier-walk work (the
        ``dispatch`` stage): applies get relabeled ``barrier_apply``
        and fragment attribution is inherited by nested walks."""
        tls = self._tls
        prev_in, prev_frag = (
            getattr(tls, "in_barrier", False),
            getattr(tls, "fragment", None),
        )
        tls.in_barrier = True
        if fragment is not None:
            tls.fragment = fragment
        try:
            yield
        finally:
            tls.in_barrier, tls.fragment = prev_in, prev_frag

    # -- summaries --------------------------------------------------------
    def executor_summary(self) -> Dict[str, Dict]:
        """The BENCH-JSON surface: executor_ms + device-wait summaries
        (per executor/fragment/phase label set: p50/p99/count/sum)."""
        out: Dict[str, Dict] = {}
        for key, hname in (
            ("executor_ms", "executor_ms"),
            ("executor_device_wait_ms", "executor_device_wait_ms"),
        ):
            h = REGISTRY.histograms.get(hname)
            if h is not None:
                out[key] = h.summary()
        return out

    def top_executors(self, n: int = 5) -> List[Dict]:
        """Ranked dispatch-cost worklist: per executor, total host ms
        (barrier phases + applies) + device wait + dispatch count —
        the fusion worklist for ROADMAP open item 1."""
        totals: Dict[str, Dict[str, float]] = {}
        for hname, field in (
            ("executor_ms", "host_ms"),
            ("executor_device_wait_ms", "device_wait_ms"),
        ):
            h = REGISTRY.histograms.get(hname)
            if h is None:
                continue
            with REGISTRY._lock:
                sums = dict(h._sum)
            for labels, s in sums.items():
                ex = dict(labels).get("executor", "-")
                d = totals.setdefault(
                    ex, {"host_ms": 0.0, "device_wait_ms": 0.0}
                )
                d[field] += s
        for ex, cnt in self.dispatch_counts().items():
            totals.setdefault(
                ex, {"host_ms": 0.0, "device_wait_ms": 0.0}
            )["dispatches"] = cnt
        ranked = sorted(
            (
                {"executor": ex, **{k: round(v, 3) for k, v in d.items()}}
                for ex, d in totals.items()
            ),
            key=lambda d: d.get("host_ms", 0.0) + d.get("device_wait_ms", 0.0),
            reverse=True,
        )
        return ranked[:n]

    def snapshot(self) -> Dict:
        """Forensic view for stall dumps: live dispatch/transfer
        counters + open capture windows."""
        return {
            "enabled": self.enabled,
            "fence": self.fence,
            "dispatches": self.dispatch_counts(),
            "kernels": self.kernel_counts(),
            "transfers": self.transfer_counts(),
            "active_captures": [
                {k: v for k, v in c.items() if k != "session"}
                for c in self.active_captures
            ],
        }

    # -- capture windows --------------------------------------------------
    def _profile_dir(self) -> str:
        return (
            self.capture_dir
            or os.environ.get("RW_PROFILE_DIR")
            or os.environ.get("RW_STALL_DIR")
            or "."
        )

    def start_capture(self, tag: str = "manual") -> Dict:
        """Open a capture window: arms ``jax.profiler.trace`` when
        ``jax_trace`` is on, and registers the window so recovery can
        audit/close it. Returns the window record."""
        d = self._profile_dir()
        with self._lock:
            self._capture_seq = getattr(self, "_capture_seq", 0) + 1
            seq = self._capture_seq
        win = {
            "tag": tag,
            "seq": seq,  # same-second captures must not collide
            "t0": time.perf_counter(),
            "ts": time.time(),
            "dir": d,
            "session": None,
        }
        if self.jax_trace:
            try:
                import jax

                trace_dir = os.path.join(
                    d, f"PROFILE_TRACE_{tag}_{int(win['ts'])}_{seq}"
                )
                jax.profiler.start_trace(trace_dir)
                win["session"] = trace_dir
                win["trace_dir"] = trace_dir
            except Exception as e:  # capture must not break the barrier
                win["trace_error"] = repr(e)
        with self._lock:
            self.active_captures.append(win)
        return win

    def end_capture(self, win: Optional[Dict] = None, extra=None) -> str:
        """Close a capture window and write the ``PROFILE_*`` JSON
        artifact (executor breakdown + counters + device forensics).
        Returns the artifact path ("" if nothing was open)."""
        with self._lock:
            if win is None:
                win = self.active_captures.pop() if self.active_captures else None
            elif win in self.active_captures:
                self.active_captures.remove(win)
        if win is None:
            return ""
        if win.get("session") is not None:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
        doc = {
            "tag": win["tag"],
            "ts": win["ts"],
            "window_s": round(time.perf_counter() - win["t0"], 4),
            "jax_trace_dir": win.get("trace_dir"),
            **self.executor_summary(),
            "device_dispatches_total": self.dispatch_counts(),
            "dispatch_kernels": self.kernel_counts(),
            "transfers": self.transfer_counts(),
            "top_executors": self.top_executors(),
            "device": device_forensics(),
        }
        # provenance: a PROFILE artifact must say which engine wrote it
        # (stale-artifact confusion is mechanically detectable)
        try:
            from risingwave_tpu.provenance import stamp

            doc.update(stamp())
        except Exception:
            pass
        # fused-stage attribution: a jax_trace capture segments the ONE
        # fused program via its named scopes — parse the trace back
        # into the per-stage split (deviceprof leg 3)
        if win.get("trace_dir"):
            try:
                from risingwave_tpu.deviceprof import parse_fused_stages

                parsed = parse_fused_stages(win["trace_dir"])
                if parsed["stages_ms"]:
                    doc["fused_stage_ms"] = parsed
            except Exception:  # noqa: BLE001 — capture must still land
                pass
        # mesh attribution (ISSUE 18): a slow-barrier capture on a
        # sharded runtime names the hot shard and the exchange phase
        # split without a separate reader pass
        try:
            from risingwave_tpu.parallel.meshprof import MESHPROF

            if MESHPROF.enabled and MESHPROF.barriers:
                doc["mesh"] = MESHPROF.barriers[-1]
        except Exception:  # noqa: BLE001 — capture must still land
            pass
        if extra:
            doc.update(extra)
        path = os.path.join(
            win["dir"],
            f"PROFILE_{win['tag']}_{int(win['ts'])}_{win.get('seq', 0)}.json",
        )
        try:
            with open(path, "w") as f:
                json.dump(doc, f, indent=1, default=str)
        except OSError:
            return ""
        try:
            from risingwave_tpu.event_log import EVENT_LOG

            EVENT_LOG.record("profile_capture", tag=win["tag"], path=path)
        except Exception:
            pass
        REGISTRY.counter("profile_captures_total").inc()
        return path

    def abort_captures(self) -> int:
        """Close every open capture window WITHOUT writing artifacts —
        the recovery path's cleanup (an orphaned jax.profiler session
        would hold the device and poison the next capture). Returns the
        number of windows closed."""
        with self._lock:
            wins, self.active_captures = self.active_captures, []
            self._capture_armed = False
        for win in wins:
            if win.get("session") is not None:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception:
                    pass
        return len(wins)

    def observe_barrier(self, wall_ms: float, runtime=None) -> Optional[str]:
        """Slow-barrier auto-capture hook (called by the runtime after
        every barrier). A barrier over ``slow_barrier_ms`` immediately
        emits a PROFILE_* artifact (counters already cover the slow
        window) and a device-forensics stall dump; bounded by
        ``max_auto_captures`` per process so a persistently slow run
        does not flood the working dir."""
        thr = self.slow_barrier_ms
        if (
            not self.enabled
            or not thr
            or wall_ms < thr
            or self._auto_captures >= self.max_auto_captures
        ):
            return None
        # spend the budget on the ATTEMPT: a failing artifact write (or
        # the dump below) must not turn a persistently slow run into an
        # unbounded per-barrier forensic loop
        self._auto_captures += 1
        win = self.start_capture(tag="slow_barrier")
        path = self.end_capture(
            win, extra={"barrier_wall_ms": round(wall_ms, 3)}
        )
        try:
            from risingwave_tpu.epoch_trace import dump_stalls

            dump_stalls(
                f"slow barrier: {wall_ms:.1f}ms >= {thr}ms profile "
                "threshold",
                runtime=runtime,
            )
        except Exception:
            pass
        return path


def device_forensics() -> Dict:
    """Device-side evidence for stall dumps / profile artifacts: HBM
    stats, a live-array census, and the accounted per-table state —
    what a q7 wedge leaves behind instead of a dead tunnel. Never
    raises; every section degrades independently."""
    out: Dict = {}
    try:
        import jax

        dev = jax.local_devices()[0]
        out["platform"] = dev.platform
        try:
            out["memory_stats"] = dev.memory_stats()  # None on CPU
        except Exception as e:
            out["memory_stats"] = repr(e)
        try:
            arrs = jax.live_arrays()
            census: Dict[str, Dict[str, float]] = {}
            total = 0
            for a in arrs:
                key = str(getattr(a, "dtype", "?"))
                nb = int(getattr(a, "nbytes", 0))
                total += nb
                d = census.setdefault(key, {"count": 0, "bytes": 0})
                d["count"] += 1
                d["bytes"] += nb
            out["live_arrays"] = {
                "total_count": len(arrs),
                "total_bytes": total,
                "by_dtype": census,
            }
        except Exception as e:
            out["live_arrays"] = repr(e)
    except Exception as e:
        out["error"] = repr(e)
    try:
        from risingwave_tpu import utils_heap

        # accounted device state by executor/state-table (top 20): the
        # fragment/state-table half of the live-array census
        out["state_tables"] = utils_heap.device_state()[:20]
    except Exception as e:
        out["state_tables"] = repr(e)
    try:
        out["profiler"] = {
            "dispatches": PROFILER.dispatch_counts(),
            "transfers": PROFILER.transfer_counts(),
            "active_captures": len(PROFILER.active_captures),
        }
    except Exception as e:  # degrade independently, like every section
        out["profiler"] = repr(e)
    try:
        # the sentinel's device classification is device evidence too:
        # a forensic artifact should say whether the heartbeat lane
        # considered the device ALIVE/SLOW/WEDGED when it was taken
        from risingwave_tpu.blackbox import SENTINEL

        out["sentinel"] = SENTINEL.snapshot()
    except Exception as e:
        out["sentinel"] = repr(e)
    return out


# the process singleton every hook consults
PROFILER = DispatchProfiler()
