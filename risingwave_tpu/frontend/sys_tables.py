"""``rw_`` system tables: the runtime's own state as SQL relations.

Reference: the reference catalog's ``rw_catalog`` schema
(src/frontend/src/catalog/system_catalog/rw_catalog/ — rw_fragments,
rw_materialized_views, rw_ddl_progress, ...): read-only virtual
relations the frontend serves straight from meta/introspection state.
Shared Arrangements' dogfooding argument (PAPERS.md) applies verbatim:
introspection should be served THROUGH the system, off the same
versioned snapshots queries read — so these tables ride the exact
lock-free ``_execute_shared_read`` path PR 12 built for shared MVs.

Each table is a ``SysTable``: a Schema plus a rows() builder over live
process state (runtime fragments, the arrangement registry, the
freshness tracker, epoch traces, permit channels, the event log). The
batch engine only ever calls ``to_numpy()`` on a scan target, so a
SysTable quacks exactly like a MaterializeExecutor snapshot: a dict of
numpy columns, VARCHAR as dictionary codes in the session's
StringDictionary. Builders read with plain attribute access + defensive
copies and NEVER take the runtime lock — a wedged barrier must remain
SELECT-able (that is the point of a stall-forensics surface).

Registration happens once per session under ``_registry_guard``
(``install_sys_tables``); the names are reserved — DDL against ``rw_``
raises in the session.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List

import numpy as np

from risingwave_tpu.types import DataType, Schema

# (name, dtype) per table; VARCHAR lanes carry dictionary codes like
# every other relation (batch._decode_output decodes them back)
SYS_SCHEMAS: Dict[str, Schema] = {
    "rw_fragments": Schema(
        [
            ("name", DataType.VARCHAR),
            ("kind", DataType.VARCHAR),
            ("executors", DataType.INT64),
            ("fused", DataType.INT64),
            ("epoch", DataType.INT64),
            ("subscribers", DataType.VARCHAR),
        ]
    ),
    "rw_arrangements": Schema(
        [
            ("owner", DataType.VARCHAR),
            ("fragment", DataType.VARCHAR),
            ("refs", DataType.INT64),
            ("shared", DataType.INT64),
            ("published_epoch", DataType.INT64),
            ("readers", DataType.VARCHAR),
        ]
    ),
    "rw_mv_freshness": Schema(
        [
            ("mv", DataType.VARCHAR),
            ("epoch", DataType.INT64),
            ("checkpoint", DataType.INT64),
            ("commit_to_visible_ms", DataType.FLOAT64),
            ("source_to_visible_ms", DataType.FLOAT64),
            ("event_time_lag_ms", DataType.FLOAT64),
            ("staleness_ms", DataType.FLOAT64),
            ("barriers", DataType.INT64),
        ]
    ),
    "rw_barrier_latency": Schema(
        [
            ("epoch", DataType.INT64),
            ("seq", DataType.INT64),
            ("checkpoint", DataType.INT64),
            ("wall_ms", DataType.FLOAT64),
            ("dispatch_ms", DataType.FLOAT64),
            ("device_step_ms", DataType.FLOAT64),
            ("backpressure_fragment", DataType.VARCHAR),
            ("backpressure_ms", DataType.FLOAT64),
        ]
    ),
    "rw_channel_depths": Schema(
        [
            ("fragment", DataType.VARCHAR),
            ("actor", DataType.VARCHAR),
            ("channel", DataType.INT64),
            ("depth", DataType.INT64),
            ("oldest_age_ms", DataType.FLOAT64),
            ("oldest_epoch", DataType.INT64),
        ]
    ),
    "rw_fusion_status": Schema(
        [
            ("fragment", DataType.VARCHAR),
            ("kind", DataType.VARCHAR),
            ("fused", DataType.INT64),
            ("fused_executors", DataType.INT64),
            ("executors", DataType.INT64),
        ]
    ),
    # integrity scrub: one row per checkpoint artifact the current
    # manifest references (plus the manifest pointer itself); status
    # in {ok, corrupt, unverified, unavailable}. A healthy store reads
    # all-ok; no store at all reads empty.
    "rw_integrity": Schema(
        [
            ("artifact", DataType.VARCHAR),
            ("table_id", DataType.VARCHAR),
            ("level", DataType.INT64),
            ("epoch", DataType.INT64),
            ("status", DataType.VARCHAR),
            ("detail", DataType.VARCHAR),
        ]
    ),
    "rw_recovery_events": Schema(
        [
            ("seq", DataType.INT64),
            ("ts_ms", DataType.INT64),
            ("mode", DataType.VARCHAR),
            ("epoch", DataType.INT64),
            ("detail", DataType.VARCHAR),
        ]
    ),
    # memory governor ledger: one row per accounted state table plus a
    # "_total" row carrying the global reconciliation (ledger vs
    # deviceprof-modeled vs sampled memory_stats) and the budget math
    "rw_memory": Schema(
        [
            ("table_id", DataType.VARCHAR),
            ("executor", DataType.VARCHAR),
            ("ledger_bytes", DataType.INT64),
            ("modeled_bytes", DataType.INT64),
            ("sampled_bytes", DataType.INT64),
            ("budget_bytes", DataType.INT64),
            ("headroom_bytes", DataType.INT64),
            ("high_water", DataType.INT64),
            ("pinned", DataType.INT64),
            ("vetoes", DataType.INT64),
        ]
    ),
    # overload ladder + admission credits: one row per fragment credit
    # window (or a single "-" row before any throttling), each carrying
    # the ladder's current rung, score, flap count and last transition
    "rw_overload_state": Schema(
        [
            ("fragment", DataType.VARCHAR),
            ("credit", DataType.FLOAT64),
            ("state", DataType.VARCHAR),
            ("score", DataType.FLOAT64),
            ("flaps", DataType.INT64),
            ("last_from", DataType.VARCHAR),
            ("last_to", DataType.VARCHAR),
            ("last_ts_ms", DataType.INT64),
            ("last_epoch", DataType.INT64),
        ]
    ),
    # mesh observability (ISSUE 18): one row per (sharded table, shard)
    # — key occupancy, rows routed in, state bytes and local-apply wall
    # from the last closed barrier window (MESHPROF.table_snapshot)
    "rw_shards": Schema(
        [
            ("table_id", DataType.VARCHAR),
            ("executor", DataType.VARCHAR),
            ("fragment", DataType.VARCHAR),
            ("shard", DataType.INT64),
            ("occupancy", DataType.INT64),
            ("rows_in", DataType.INT64),
            ("rows_in_total", DataType.INT64),
            ("state_bytes", DataType.INT64),
            ("local_ms", DataType.FLOAT64),
            ("skew_ratio", DataType.FLOAT64),
            ("is_hot", DataType.INT64),
        ]
    ),
    # exchange-cost matrix: one row per (src, dst) shard pair with
    # cumulative and last-barrier routed rows/bytes over all-to-all
    "rw_exchange": Schema(
        [
            ("src", DataType.INT64),
            ("dst", DataType.INT64),
            ("rows_total", DataType.INT64),
            ("bytes_total", DataType.INT64),
            ("rows_last", DataType.INT64),
            ("bytes_last", DataType.INT64),
        ]
    ),
}


class SysTable:
    """A read-only virtual relation over live introspection state.

    Quacks like a registered MV for the batch engine's scan path: the
    only method the engine calls on a ``P.TableRef`` target is
    ``to_numpy()``. A failing builder degrades to an empty relation —
    introspection never turns a SELECT into a 500."""

    def __init__(
        self, name: str, schema: Schema, rows: Callable, session
    ):
        self.name = name
        self.schema = schema
        self._rows = rows
        self._session = session

    def to_numpy(self) -> Dict[str, np.ndarray]:
        try:
            rows = self._rows(self._session)
        except Exception:  # noqa: BLE001 — introspection never faults
            rows = []
        enc = self._session.strings.encode_one
        out: Dict[str, np.ndarray] = {}
        for f in self.schema.fields:
            vals = [r.get(f.name) for r in rows]
            if f.dtype is DataType.VARCHAR:
                out[f.name] = np.asarray(
                    [enc("" if v is None else str(v)) for v in vals],
                    np.int32,
                )
            elif f.dtype is DataType.FLOAT64:
                out[f.name] = np.asarray(
                    [float(v) if v is not None else -1.0 for v in vals],
                    np.float64,
                )
            else:
                out[f.name] = np.asarray(
                    [int(v) if v is not None else 0 for v in vals],
                    np.int64,
                )
        return out


# -- row builders (one per table) -------------------------------------------


def _fused_count(p) -> int:
    """Fused wrappers visible in a fragment: the in-place serial/two-
    input wrappers plus any inside a graph's actor chains."""
    n = 0
    if getattr(p, "_fused", None) is not None:
        n += 1
    for ex in getattr(p, "executors", ()) or ():
        if type(ex).__name__.startswith("Fused"):
            n += 1
    g = getattr(p, "graph", None)
    if g is not None:
        for a in getattr(g, "actors", ()) or ():
            for ex in getattr(a, "executors", ()) or ():
                if type(ex).__name__.startswith("Fused"):
                    n += 1
    return n


def _rows_fragments(session) -> List[dict]:
    rt = session.runtime
    rows = []
    for name in sorted(getattr(rt, "fragments", {})):
        p = rt.fragments[name]
        subs = [d for d, _s in getattr(rt, "_subs", {}).get(name, ())]
        rows.append(
            {
                "name": name,
                "kind": type(p).__name__,
                "executors": len(getattr(p, "executors", ()) or ()),
                "fused": 1 if _fused_count(p) else 0,
                "epoch": getattr(p, "_epoch", 0),
                "subscribers": ",".join(subs),
            }
        )
    return rows


def _rows_arrangements(session) -> List[dict]:
    reg = getattr(session.runtime, "arrangements", None)
    if reg is None:
        return []
    rows = []
    for arr in list(getattr(reg, "_live", ()) or ()):
        ver = getattr(arr, "version", None)
        rows.append(
            {
                "owner": getattr(arr, "owner", ""),
                "fragment": getattr(arr, "fragment", ""),
                "refs": len(getattr(arr, "refs", ()) or ()),
                "shared": int(
                    len(getattr(arr, "refs", ()) or ()) > 1
                    or getattr(arr, "hidden", False)
                ),
                "published_epoch": getattr(ver, "epoch", 0) or 0,
                "readers": ",".join(sorted(getattr(arr, "refs", ()) or ())),
            }
        )
    rows.sort(key=lambda r: r["owner"])
    return rows


def _rows_mv_freshness(session) -> List[dict]:
    from risingwave_tpu.freshness import FRESHNESS

    now = time.time()
    rows = []
    for r in FRESHNESS.snapshot():
        rows.append(
            {
                "mv": r["mv"],
                "epoch": r["epoch"],
                "checkpoint": int(r["checkpoint"]),
                "commit_to_visible_ms": r["commit_to_visible_ms"],
                "source_to_visible_ms": r["source_to_visible_ms"],
                "event_time_lag_ms": r["event_time_lag_ms"],
                # live staleness: how long ago this MV's snapshot became
                # visible — monotone between barriers, resets at publish
                "staleness_ms": round((now - r["visible_at"]) * 1e3, 3),
                "barriers": r["barriers"],
            }
        )
    return rows


def _rows_barrier_latency(session) -> List[dict]:
    rt = session.runtime
    traces = list(getattr(rt, "epoch_traces", ()) or ())[-128:]
    rows = []
    for tr in traces:
        st = getattr(tr, "stages_ms", {}) or {}
        rows.append(
            {
                "epoch": getattr(tr, "epoch", 0),
                "seq": getattr(tr, "seq", 0),
                "checkpoint": int(getattr(tr, "checkpoint", False)),
                "wall_ms": round(getattr(tr, "wall_ms", 0.0), 3),
                "dispatch_ms": round(st.get("dispatch", 0.0), 3),
                "device_step_ms": round(st.get("device_step", 0.0), 3),
                "backpressure_fragment": getattr(
                    tr, "backpressure_fragment", None
                )
                or "",
                "backpressure_ms": round(
                    getattr(tr, "backpressure_ms", 0.0), 3
                ),
            }
        )
    return rows


def _rows_channel_depths(session) -> List[dict]:
    rt = session.runtime
    rows = []
    for name in sorted(getattr(rt, "fragments", {})):
        g = getattr(rt.fragments[name], "graph", None)
        if g is None:
            continue
        for a in getattr(g, "actors", ()) or ():
            for i, (_port, ch) in enumerate(a.inputs):
                op = ch.oldest_pending()
                rows.append(
                    {
                        "fragment": name,
                        "actor": a.actor_name,
                        "channel": i,
                        "depth": len(ch),
                        "oldest_age_ms": (
                            round(op["age_ms"], 3) if op else None
                        ),
                        "oldest_epoch": op["epoch"] if op else None,
                    }
                )
    return rows


def _rows_fusion_status(session) -> List[dict]:
    rt = session.runtime
    rows = []
    for name in sorted(getattr(rt, "fragments", {})):
        p = rt.fragments[name]
        fused = _fused_count(p)
        rows.append(
            {
                "fragment": name,
                "kind": type(p).__name__,
                "fused": int(fused > 0),
                "fused_executors": fused,
                "executors": len(getattr(p, "executors", ()) or ()),
            }
        )
    return rows


def _rows_recovery_events(session) -> List[dict]:
    from risingwave_tpu.event_log import EVENT_LOG

    rows = []
    for e in EVENT_LOG.events(kind="recovery", limit=256):
        detail = ",".join(
            f"{k}={v}"
            for k, v in sorted(e.items())
            if k not in ("seq", "ts", "kind", "mode", "epoch")
        )
        rows.append(
            {
                "seq": e["seq"],
                "ts_ms": int(e["ts"] * 1000),
                "mode": e.get("mode", ""),
                "epoch": e.get("epoch"),
                "detail": detail,
            }
        )
    return rows


def _rows_integrity(session) -> List[dict]:
    mgr = getattr(session.runtime, "mgr", None)
    if mgr is None:
        return []
    return mgr.scrub()


def _rows_memory(session) -> List[dict]:
    gov = getattr(session.runtime, "memory_governor", None)
    if gov is None:
        return []
    snap = gov.snapshot()
    rows = []
    for t in gov.ledger_snapshot():
        rows.append(
            {
                "table_id": t["table_id"],
                "executor": t["executor"],
                "ledger_bytes": t["ledger_bytes"],
                "modeled_bytes": None,
                "sampled_bytes": None,
                "budget_bytes": None,
                "headroom_bytes": None,
                "high_water": t["high_water"],
                "pinned": int(t["pinned"]),
                "vetoes": t["vetoes"],
            }
        )
    rows.sort(key=lambda r: -r["ledger_bytes"])
    # per-shard breakdown (ISSUE 18): sharded tables get one sub-row
    # per shard after the table rows, keyed "<table_id>/shard<i>"
    shard_rows = []
    for t in gov.ledger_snapshot():
        for i, b in enumerate(t.get("shards") or ()):
            shard_rows.append(
                {
                    "table_id": f"{t['table_id']}/shard{i}",
                    "executor": t["executor"],
                    "ledger_bytes": b,
                    "modeled_bytes": None,
                    "sampled_bytes": None,
                    "budget_bytes": None,
                    "headroom_bytes": None,
                    "high_water": None,
                    "pinned": None,
                    "vetoes": None,
                }
            )
    rows.extend(shard_rows)
    rows.append(
        {
            "table_id": "_total",
            "executor": "-",
            "ledger_bytes": snap["ledger_bytes"],
            "modeled_bytes": snap["modeled_bytes"],
            "sampled_bytes": snap["sampled_bytes"],
            "budget_bytes": snap["budget_bytes"],
            "headroom_bytes": snap["headroom_bytes"],
            "high_water": None,
            "pinned": None,
            "vetoes": snap["vetoes"],
        }
    )
    return rows


def _rows_shards(session) -> List[dict]:
    from risingwave_tpu.parallel.meshprof import MESHPROF

    snap = MESHPROF.table_snapshot()
    last = snap.get("last_barrier") or {}
    skew = last.get("skew") or {}
    rows = []
    for tid, t in (snap.get("tables") or {}).items():
        n = int(t.get("n_shards") or 0)
        rin_last = t.get("rows_in_last") or []
        rin_tot = t.get("rows_in_total") or []
        occ = t.get("occupancy") or []
        sb = t.get("state_bytes_per_shard") or []
        loc = (last.get("shard_local_ms") or []) if last else []
        for i in range(n):
            hot = int(
                skew.get("table_id") == tid and skew.get("shard") == i
            )
            rows.append(
                {
                    "table_id": tid,
                    "executor": t.get("executor", ""),
                    "fragment": t.get("pipeline", ""),
                    "shard": i,
                    "occupancy": occ[i] if i < len(occ) else None,
                    "rows_in": rin_last[i] if i < len(rin_last) else 0,
                    "rows_in_total": (
                        rin_tot[i] if i < len(rin_tot) else 0
                    ),
                    "state_bytes": sb[i] if i < len(sb) else None,
                    "local_ms": loc[i] if i < len(loc) else None,
                    "skew_ratio": t.get("skew_ratio_last"),
                    "is_hot": hot,
                }
            )
    return rows


def _rows_exchange(session) -> List[dict]:
    from risingwave_tpu.parallel.meshprof import MESHPROF

    ex = MESHPROF.table_snapshot().get("exchange") or {}
    rows_m = ex.get("rows") or []
    bytes_m = ex.get("bytes") or []
    rows_l = ex.get("rows_last") or []
    bytes_l = ex.get("bytes_last") or []

    def _cell(m, i, j):
        try:
            return int(m[i][j])
        except (IndexError, TypeError):
            return 0

    out = []
    for i, row in enumerate(rows_m):
        for j in range(len(row)):
            out.append(
                {
                    "src": i,
                    "dst": j,
                    "rows_total": _cell(rows_m, i, j),
                    "bytes_total": _cell(bytes_m, i, j),
                    "rows_last": _cell(rows_l, i, j),
                    "bytes_last": _cell(bytes_l, i, j),
                }
            )
    return out


def _rows_overload_state(session) -> List[dict]:
    gov = getattr(session.runtime, "memory_governor", None)
    if gov is None:
        return []
    lad = gov.ladder.snapshot()
    last = (lad["transitions"] or [{}])[-1]
    base = {
        "state": lad["state"],
        "score": lad["score"],
        "flaps": lad["flaps"],
        "last_from": last.get("from", ""),
        "last_to": last.get("to", ""),
        "last_ts_ms": (
            int(last["ts"] * 1000) if last.get("ts") is not None else None
        ),
        "last_epoch": last.get("epoch"),
    }
    credits = gov.admission.credits
    if not credits:
        return [dict(base, fragment="-", credit=1.0)]
    return [
        dict(base, fragment=frag, credit=c)
        for frag, c in sorted(credits.items())
    ]


_BUILDERS: Dict[str, Callable] = {
    "rw_fragments": _rows_fragments,
    "rw_arrangements": _rows_arrangements,
    "rw_mv_freshness": _rows_mv_freshness,
    "rw_barrier_latency": _rows_barrier_latency,
    "rw_channel_depths": _rows_channel_depths,
    "rw_fusion_status": _rows_fusion_status,
    "rw_integrity": _rows_integrity,
    "rw_recovery_events": _rows_recovery_events,
    "rw_memory": _rows_memory,
    "rw_overload_state": _rows_overload_state,
    "rw_shards": _rows_shards,
    "rw_exchange": _rows_exchange,
}


def install_sys_tables(session) -> None:
    """Register every ``rw_`` relation into the session's catalog +
    batch engine (idempotent; called from SqlSession.__init__ under
    ``_registry_guard``). The catalog entry makes typecheck_select see
    them; the batch entry makes the scan path find them; the
    ``_execute_shared_read`` branch serves them without the session
    lock."""
    for name, schema in SYS_SCHEMAS.items():
        session.catalog.tables[name] = schema
        session.batch.register(
            name, SysTable(name, schema, _BUILDERS[name], session)
        )
