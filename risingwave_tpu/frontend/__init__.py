"""Frontend — SQL session + Postgres wire protocol surface."""

from risingwave_tpu.frontend.session import SqlSession
from risingwave_tpu.frontend.pgwire import PgServer

__all__ = ["PgServer", "SqlSession"]
