"""SqlSession — one entry point for every statement kind.

Reference: src/frontend/src/handler/mod.rs routes parsed statements to
handlers (create_mv, dml, query); the session owns the catalog and
talks to meta/batch/stream. Here it ties together:

- CREATE MATERIALIZED VIEW -> StreamPlanner -> runtime.register
  (with MV-on-MV backfill when the input is itself an MV) +
  catalog/DML/batch registration;
- INSERT INTO -> DmlManager (rows pushed into consuming fragments);
- SELECT -> BatchQueryEngine over MV snapshots.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from risingwave_tpu.batch.engine import BatchQueryEngine
from risingwave_tpu.runtime import DmlManager, StreamingRuntime
from risingwave_tpu.sql import Catalog, StreamPlanner
from risingwave_tpu.sql import parser as P


class SqlSession:
    def __init__(
        self,
        catalog: Catalog,
        runtime: Optional[StreamingRuntime] = None,
        capacity: int = 1 << 14,
    ):
        self.catalog = catalog
        self.runtime = runtime or StreamingRuntime(store=None)
        self.planner = StreamPlanner(catalog, capacity=capacity)
        self.batch = BatchQueryEngine({})
        self.dml = DmlManager(self.runtime, catalog)

    def execute(self, sql: str) -> Tuple[Dict[str, np.ndarray], str]:
        """Returns (result columns, command tag). Non-queries return an
        empty column dict."""
        stmt = P.parse(sql)
        if isinstance(stmt, P.CreateMaterializedView):
            planned = self.planner.plan(sql)
            upstreams = [
                s for s in planned.inputs if self.catalog.is_mv(s)
            ]
            self.runtime.register(
                planned.name,
                planned.pipeline,
                upstream=upstreams[0] if upstreams else None,
            )
            self.catalog.add_mv(planned)
            self.dml.attach(planned)
            self.batch.register(planned.name, planned.mview)
            return {}, "CREATE_MATERIALIZED_VIEW"
        if isinstance(stmt, P.InsertValues):
            n = self.dml.execute(sql)
            # DML visibility: the reference commits DML at the next
            # checkpoint barrier; interactive sessions read their own
            # writes, so advance the barrier clock here
            self.runtime.barrier()
            return {}, f"INSERT 0 {n}"
        out = self.batch.query(sql)
        n = len(next(iter(out.values()))) if out else 0
        return out, f"SELECT {n}"
