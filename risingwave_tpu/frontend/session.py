"""SqlSession — one entry point for every statement kind.

Reference: src/frontend/src/handler/mod.rs routes parsed statements to
handlers (create_mv, dml, query); the session owns the catalog and
talks to meta/batch/stream. Here it ties together:

- CREATE MATERIALIZED VIEW -> StreamPlanner -> runtime.register
  (with MV-on-MV backfill when the input is itself an MV) +
  catalog/DML/batch registration;
- INSERT INTO -> DmlManager (rows pushed into consuming fragments);
- SELECT -> BatchQueryEngine over MV snapshots.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from risingwave_tpu.batch.engine import BatchQueryEngine
from risingwave_tpu.runtime import DmlManager, StreamingRuntime
from risingwave_tpu.sql import Catalog, StreamPlanner
from risingwave_tpu.sql import parser as P
from risingwave_tpu.types import DataType, Schema

_TYPE_WORDS = {
    "int": DataType.INT32, "integer": DataType.INT32, "int4": DataType.INT32,
    "bigint": DataType.INT64, "int8": DataType.INT64, "int64": DataType.INT64,
    "real": DataType.FLOAT32, "float4": DataType.FLOAT32,
    "double": DataType.FLOAT64, "float8": DataType.FLOAT64,
    "boolean": DataType.BOOLEAN, "bool": DataType.BOOLEAN,
    "timestamp": DataType.TIMESTAMP,
    "varchar": DataType.VARCHAR, "text": DataType.VARCHAR,
}


class SqlSession:
    def __init__(
        self,
        catalog: Catalog,
        runtime: Optional[StreamingRuntime] = None,
        capacity: int = 1 << 14,
    ):
        self.catalog = catalog
        self.runtime = runtime or StreamingRuntime(store=None)
        self.planner = StreamPlanner(catalog, capacity=capacity)
        self.batch = BatchQueryEngine({})
        self.dml = DmlManager(self.runtime, catalog)

    def execute(self, sql: str) -> Tuple[Dict[str, np.ndarray], str]:
        """Returns (result columns, command tag). Non-queries return an
        empty column dict."""
        with self.runtime.lock:
            return self._execute_locked(sql)

    def _execute_locked(self, sql: str) -> Tuple[Dict[str, np.ndarray], str]:
        stmt = P.parse(sql)
        if isinstance(stmt, P.CreateTable):
            if (
                stmt.name in self.catalog.tables
                or stmt.name in self.runtime.fragments
            ):
                raise ValueError(f"relation {stmt.name!r} already exists")
            fields = []
            for cname, tword in stmt.columns:
                dt = _TYPE_WORDS.get(tword.lower())
                if dt is None:
                    raise ValueError(f"unknown type {tword!r}")
                fields.append((cname, dt))
            schema = Schema(fields)
            self.catalog.tables[stmt.name] = schema
            # a table IS a materialized relation (create_table.rs makes
            # the same plan: dml -> row-id gen -> materialize): give it
            # a fragment so INSERTs land somewhere queryable and
            # downstream MVs backfill from its snapshot
            from risingwave_tpu.executors.materialize import (
                MaterializeExecutor,
            )
            from risingwave_tpu.executors.row_id_gen import RowIdGenExecutor
            from risingwave_tpu.runtime import Pipeline

            mview = MaterializeExecutor(
                pk=("_row_id",),
                columns=schema.names,
                table_id=f"{stmt.name}.table",
            )
            self.runtime.register(
                stmt.name,
                Pipeline(
                    [
                        RowIdGenExecutor(
                            out_col="_row_id",
                            table_id=f"{stmt.name}.rowid",
                        ),
                        mview,
                    ]
                ),
            )
            self.batch.register(stmt.name, mview)
            self.dml.add_target(stmt.name, stmt.name, "single")
            return {}, "CREATE_TABLE"
        if isinstance(stmt, P.CreateMaterializedView):
            planned = self.planner.plan(sql)
            if planned.name in self.runtime.fragments:
                raise ValueError(
                    f"relation {planned.name!r} already exists"
                )
            # each input is either an existing fragment (table / MV):
            # subscribe its delta edge with the correct join side and
            # backfill from its snapshot — or a raw base stream: attach
            # a DML target so INSERTs land in this MV directly
            frag_inputs = {
                s: side
                for s, side in planned.inputs.items()
                if s in self.runtime.fragments
            }
            self.runtime.register(planned.name, planned.pipeline)
            try:
                for s, side in frag_inputs.items():
                    self.runtime.subscribe(s, planned.name, side=side)
            except BaseException:
                # keep the graph consistent on backfill failure: a
                # half-registered fragment would crash later barriers
                self.runtime.unregister(planned.name)
                raise
            self.catalog.add_mv(planned)
            if len(frag_inputs) < len(planned.inputs):
                self.dml.attach(planned, skip=frag_inputs.keys())
            self.batch.register(planned.name, planned.mview)
            # CREATE returns once the backfill snapshot is visible
            # (the reference blocks DDL on backfill completion)
            self.runtime.barrier()
            return {}, "CREATE_MATERIALIZED_VIEW"
        if isinstance(stmt, P.InsertValues):
            n = self.dml.execute(sql)
            # DML visibility: the reference commits DML at the next
            # checkpoint barrier; interactive sessions read their own
            # writes, so advance the barrier clock here
            self.runtime.barrier()
            return {}, f"INSERT 0 {n}"
        out = self.batch.query(sql)
        n = len(next(iter(out.values()))) if out else 0
        return out, f"SELECT {n}"
