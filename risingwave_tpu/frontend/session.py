"""SqlSession — one entry point for every statement kind.

Reference: src/frontend/src/handler/mod.rs routes parsed statements to
handlers (create_mv, dml, query); the session owns the catalog and
talks to meta/batch/stream. Here it ties together:

- CREATE MATERIALIZED VIEW -> StreamPlanner -> runtime.register
  (with MV-on-MV backfill when the input is itself an MV) +
  catalog/DML/batch registration;
- INSERT INTO -> DmlManager (rows pushed into consuming fragments);
- SELECT -> BatchQueryEngine over MV snapshots.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from risingwave_tpu.batch.engine import BatchQueryEngine
from risingwave_tpu.runtime import DmlManager, StreamingRuntime
from risingwave_tpu.sql import Catalog, StreamPlanner
from risingwave_tpu.sql import parser as P
from risingwave_tpu.types import DataType, Field, Schema

_TYPE_WORDS = {
    "int": DataType.INT32, "integer": DataType.INT32, "int4": DataType.INT32,
    "bigint": DataType.INT64, "int8": DataType.INT64, "int64": DataType.INT64,
    "real": DataType.FLOAT32, "float4": DataType.FLOAT32,
    "double": DataType.FLOAT64, "float8": DataType.FLOAT64,
    "boolean": DataType.BOOLEAN, "bool": DataType.BOOLEAN,
    "timestamp": DataType.TIMESTAMP,
    "varchar": DataType.VARCHAR, "text": DataType.VARCHAR,
    "decimal": DataType.DECIMAL, "numeric": DataType.DECIMAL,
    "interval": DataType.INTERVAL,
    "jsonb": DataType.JSONB, "json": DataType.JSONB,
    "int256": DataType.INT256, "rw_int256": DataType.INT256,
}


def _parse_type_word(cname: str, tword: str):
    """'decimal(10,2)' / 'varchar(64)' / plain words -> Field."""
    base, _, args = tword.partition("(")
    dt = _TYPE_WORDS.get(base.lower())
    if dt is None:
        raise ValueError(f"unknown type {tword!r}")
    if dt.is_composite:
        # interval/struct/list decompose into multiple device lanes;
        # the SELECT result edge and the MV planner do not reassemble
        # them yet — usable via the Python chunk API (array/composite),
        # not via DDL (accepting them here made SELECT crash later)
        raise NotImplementedError(
            f"column {cname!r}: composite type {base.upper()} is not "
            "SQL-addressable yet (supported via the Python chunk API)"
        )
    scale = None
    if dt is DataType.DECIMAL and args:
        parts = args.rstrip(")").split(",")
        scale = int(parts[1]) if len(parts) > 1 else 0
    return Field(cname, dt, scale=scale)


class _AttachedMV:
    """Catalog marker for an MV attached to a shared arrangement
    (runtime/arrangements.py): it owns no pipeline and no state —
    reads go through the published-version facade, DROP decrements
    the arrangement refcount. ``mview`` quacks enough like a
    MaterializeExecutor (pk/columns/to_numpy/snapshot) for the batch
    engine and MV-on-MV planning."""

    def __init__(self, name, arrangement, facade):
        self.name = name
        self.arrangement = arrangement
        self.mview = facade
        self.pipeline = None
        self.inputs: Dict[str, str] = {}
        self.aux = ()
        self.schema = arrangement.schema


class SqlSession:
    def __init__(
        self,
        catalog: Catalog,
        runtime: Optional[StreamingRuntime] = None,
        capacity: int = 1 << 14,
        exec_mode: str = "serial",
        parallelism: int = 1,
        hub=None,
        strict_lint: Optional[bool] = None,
    ):
        from risingwave_tpu.array.dictionary import StringDictionary

        if exec_mode not in ("serial", "graph"):
            raise ValueError(f"unknown exec_mode {exec_mode!r}")
        # rwlint at CREATE-MV time (analysis/): every planned MV is
        # verified before actors spawn; with strict_lint, an
        # error-severity diagnostic refuses the DDL (PlanLintError).
        # Default comes from RW_STRICT_LINT (on unless set to 0) so the
        # whole test suite self-applies the verifier.
        if strict_lint is None:
            import os

            strict_lint = os.environ.get(
                "RW_STRICT_LINT", "1"
            ).strip().lower() not in ("0", "off", "false")
        self.strict_lint = bool(strict_lint)
        # (name, Diagnostic) per CREATE MV, in DDL order — the CLI's
        # SQL-file lint surface reads this
        self.lint_findings = []
        self.catalog = catalog
        self.runtime = runtime or StreamingRuntime(store=None)
        self.capacity = capacity
        # "serial": host-driven executor chains; "graph": the unified
        # actor path — fragment graph with dispatchers/permit channels,
        # hash-partitioned across ``parallelism`` actors where the plan
        # shape allows (runtime/fragmenter.py)
        self.exec_mode = exec_mode
        self.parallelism = parallelism
        self.planner = StreamPlanner(catalog, capacity=capacity)
        self.batch = BatchQueryEngine({})
        # one session dictionary backs every VARCHAR/JSONB column: codes
        # are equality-complete across relations, so joins/group-bys on
        # strings compare codes (array/dictionary.py)
        self.strings = StringDictionary()
        self.planner.strings = self.strings  # literal -> code rewriting
        self.batch.strings = self.strings  # string_agg joins decoded text
        self.batch.catalog = catalog  # collect-agg element decoding
        # temporal joins probe a relation's materialize state directly
        self.planner.mviews = self.batch.tables
        self.dml = DmlManager(self.runtime, catalog, strings=self.strings)
        # CREATE SOURCE registry: name -> GenericSourceExecutor
        self.sources: Dict[str, object] = {}
        # split-to-worker assignment authority (SourceManager,
        # source_manager.rs): discovery + rebalancing + per-worker
        # disjoint polling (the SourceChangeSplit analogue)
        from risingwave_tpu.runtime import SourceManager

        self.source_mgr = SourceManager()
        # NotificationHub (manager/notification.rs + the frontend
        # ObserverManager): sessions sharing one runtime observe each
        # other's catalog mutations with versioned catch-up
        self.hub = hub
        self._hub_oid = None
        if hub is not None:
            self._hub_oid = hub.subscribe(self._apply_notification)
        self._register_string_builtins()
        self._replaying = False
        # catalog/batch-registry mutation guard: the shared-arrangement
        # read path serves SELECTs WITHOUT the runtime lock, so every
        # catalog/batch mutation (CREATE/DROP) must be atomic against
        # those concurrent readers — mutations take this lock briefly;
        # readers re-check under it only on a race (fallback path)
        self._registry_guard = threading.RLock()
        # attached-name -> dependent MV names: an MV built OVER an
        # attached shared MV subscribes to the WRITER fragment, so the
        # runtime's _subs edges never carry the attached name — this
        # map keeps the DROP dependency guard honest for it
        self._attached_deps: Dict[str, set] = {}
        # rw_ system tables (sys_tables.py): the runtime's own state as
        # read-only relations, served over the SAME lock-free shared-
        # read path as attached arrangements
        from risingwave_tpu.frontend.sys_tables import install_sys_tables

        with self._registry_guard:
            install_sys_tables(self)
        self.meta = None
        if getattr(self.runtime, "mgr", None) is not None:
            # durable meta: DDL log + dictionary snapshots ride the
            # same object store as Hummock state (storage/meta_backup)
            from risingwave_tpu.storage.meta_backup import (
                DictionaryPersistor,
                MetaStore,
            )

            self.meta = MetaStore(self.runtime.mgr.store)
            dump = self.meta.load_strings()
            if dump:
                for t in dump:
                    self.strings.encode_one(t)
            self.runtime.register_state(
                DictionaryPersistor(self.strings, self.meta)
            )

    @classmethod
    def restore(
        cls,
        runtime: StreamingRuntime,
        capacity: int = 1 << 14,
        exec_mode: str = "serial",
        parallelism: int = 1,
        strict_lint: Optional[bool] = None,
    ):
        """Bootstrap a session from a durable store: replay the DDL log
        (structure only — no barriers, no backfill), then recover every
        executor's state from the last committed epoch (the reference's
        cluster bootstrap: catalog load + recovery.rs:353)."""
        session = cls(
            Catalog({}),
            runtime,
            capacity=capacity,
            exec_mode=exec_mode,
            parallelism=parallelism,
            strict_lint=strict_lint,
        )
        if session.meta is None:
            raise ValueError("restore needs a runtime with an object store")
        session._replaying = True
        try:
            for sql in session.meta.ddl():
                session.execute(sql)
        finally:
            session._replaying = False
        runtime.recover()
        return session

    def _log_ddl(self, sql: str) -> None:
        if self.meta is not None and not self._replaying:
            self.meta.append_ddl(sql)

    # -- notifications (observer manager) --------------------------------
    def _notify(self, op: str, kind: str, name: str, **payload) -> None:
        if self.hub is not None:
            payload["origin"] = id(self)
            self.hub.publish(op, kind, name, payload)

    def _apply_notification(self, n) -> None:
        """Apply a peer session's catalog mutation (the frontend
        observer role, observer_manager.rs:40): this session gains
        READ/WRITE access to the relation without owning its fragment
        registration (the shared runtime already runs it)."""
        if n.payload.get("origin") == id(self):
            return  # self-echo
        if n.op == "drop":
            self.catalog.mvs.pop(n.name, None)
            self.catalog.tables.pop(n.name, None)
            self.catalog.watermarks.pop(n.name, None)
            self.batch.tables.pop(n.name, None)
            self.sources.pop(n.name, None)
            self.source_mgr.unregister(n.name)
            self.dml.detach_fragment(n.name)
            return
        if "schema" not in n.payload:
            # payload freed by a later drop (the hub compacts dropped
            # relations): the following drop in the backlog cancels it
            return
        if n.kind in ("table", "mv"):
            self.catalog.tables[n.name] = n.payload["schema"]
            if n.payload.get("mview") is not None:
                self.batch.register(n.name, n.payload["mview"])
            if n.kind == "mv" and n.payload.get("planned") is not None:
                self.catalog.mvs[n.name] = n.payload["planned"]
            elif n.kind == "table" and n.payload.get("writable", True):
                # peer INSERTs route into the SHARED runtime fragment
                self.dml.add_target(n.name, n.name, "single")
        elif n.kind == "source":
            self.catalog.tables[n.name] = n.payload["schema"]
            # the SAME executor object (shared offsets: whoever pumps
            # first wins each record exactly once); registering it in
            # this session's manager makes MVs created HERE pumpable
            self.sources.setdefault(n.name, n.payload["src"])
            if n.name not in self.source_mgr:
                self.source_mgr.register(
                    n.name, n.payload["src"], parallelism=1
                )

    def close(self) -> None:
        """Detach from the hub: a discarded session must not keep
        receiving (and acting on) peers' DDL, nor be kept alive by the
        hub's observer table."""
        if self.hub is not None and self._hub_oid is not None:
            self.hub.unsubscribe(self._hub_oid)
            self._hub_oid = None

    def _fresh_planner(self) -> StreamPlanner:
        """A fresh planner per graph-mode instance: deterministic
        table_ids (instances are vnode partitions of the SAME logical
        tables) with this session's dictionary/temporal bindings."""
        p = StreamPlanner(self.catalog, capacity=self.capacity)
        p.strings = self.strings
        p.mviews = self.batch.tables
        return p

    def execute(self, sql: str) -> Tuple[Dict[str, np.ndarray], str]:
        """Returns (result columns, command tag). Non-queries return an
        empty column dict.

        SELECTs over shared-arrangement subscriber MVs are served OFF
        the published per-barrier version WITHOUT the runtime lock (the
        serving tier: N concurrent pgwire readers never contend with
        the barrier clock or each other) — everything else serializes
        through the runtime lock as before."""
        fast = self._execute_shared_read(sql)
        if fast is not None:
            return fast
        with self.runtime.lock:
            out, tag = self._execute_locked(sql)
        if tag.startswith(("CREATE_", "DROP_", "ALTER_")):
            # meta event log: every DDL lands in cluster history
            from risingwave_tpu.event_log import EVENT_LOG

            EVENT_LOG.record("ddl", tag=tag, sql=sql.strip()[:200])
        return out, tag

    def _execute_shared_read(
        self, sql: str
    ) -> Optional[Tuple[Dict[str, np.ndarray], str]]:
        """The lock-free serving path: a plain SELECT whose FROM is a
        shared-arrangement subscriber evaluates against the published
        (immutable, barrier-consistent) snapshot — no runtime lock, no
        torn reads, no contention with streaming. Returns None for
        anything this path does not cover (the locked path then runs
        it, including raising its real errors)."""
        stripped = sql.lstrip()
        if stripped[:7].lower() != "select ":
            return None
        reg = getattr(self.runtime, "arrangements", None)
        # cheap eligibility probe BEFORE the speculative parse: reads
        # over non-served relations must not pay a double parse+
        # typecheck on the hot path (the locked path parses again).
        # Served names: shared-arrangement subscribers AND rw_ system
        # tables (sys_tables.py — introspection snapshots are immutable
        # per call, so they need the runtime lock even less)
        import re as _re

        m = _re.search(r"(?is)\bfrom\s+([A-Za-z_]\w*)", stripped)
        if m is None:
            return None
        name = m.group(1)

        def _served(n: str) -> bool:
            if n.startswith("rw_") and n in self.batch.tables:
                return True
            return reg is not None and reg._facades and reg.serves(n)

        if not _served(name):
            return None
        try:
            stmt = P.parse(sql)
            if not isinstance(stmt, P.Select) or not isinstance(
                stmt.from_, P.TableRef
            ):
                return None
            if not _served(stmt.from_.name):
                return None
            from risingwave_tpu.sql.typing import typecheck_select

            stmt = typecheck_select(stmt, self.catalog, self.strings)
            out = self.batch.query(sql, stmt=stmt)
            out = self._decode_output(stmt, out)
        except Exception:  # noqa: BLE001 — races/feature gaps fall back
            # anything surprising (a DROP racing this read, a shape the
            # fast path mishandles) re-runs under the runtime lock,
            # which either serves it or raises the genuine error
            return None
        n = len(next(iter(out.values()))) if out else 0
        return out, f"SELECT {n}"

    def _execute_locked(self, sql: str) -> Tuple[Dict[str, np.ndarray], str]:
        stripped = sql.lstrip()
        if stripped[:13].lower().startswith("create source"):
            return self._create_source(stripped)
        if stripped[:12].lower().startswith("alter source"):
            # ALTER SOURCE name SET rate_limit = N | DEFAULT — the
            # reference's throttle mutation (Mutation::Throttle,
            # handler/alter_streaming_rate_limit.rs); applies from the
            # next poll in the host-pumped model
            import re

            m = re.match(
                r"(?is)^alter\s+source\s+(\w+)\s+set\s+rate_limit\s*=\s*"
                r"(\d+|default)\s*;?\s*$",
                stripped,
            )
            if not m:
                raise SyntaxError(
                    "ALTER SOURCE <name> SET rate_limit = <rows/s|DEFAULT>"
                )
            name, val = m.group(1), m.group(2).lower()
            if name not in self.sources:
                raise KeyError(f"unknown source {name!r}")
            self.sources[name].set_rate_limit(
                None if val == "default" else int(val)
            )
            # the throttle is operator-visible config: it must survive
            # a restore (the DDL log replays this statement)
            self._log_ddl(stripped)
            return {}, "ALTER_SOURCE"
        if stripped[:15].lower().startswith("create function"):
            return self._create_function(stripped)
        low = stripped.lower()
        if low.startswith(("drop materialized view", "drop table", "drop source")):
            return self._execute_drop(stripped)
        if stripped[:13].lower().startswith("drop function"):
            import re

            from risingwave_tpu.expr import functions as F

            m = re.match(r"(?is)^drop\s+function\s+(\w+)\s*;?\s*$", stripped)
            if not m:
                raise SyntaxError("DROP FUNCTION <name>")
            if F.is_protected(m.group(1)):
                raise ValueError(
                    f"{m.group(1)!r} is a builtin function and cannot "
                    "be dropped"
                )
            if not F.drop_function(m.group(1)):
                raise KeyError(f"unknown function {m.group(1)!r}")
            self._log_ddl(stripped)
            return {}, "DROP_FUNCTION"
        if stripped[:12].lower().startswith("create index"):
            return self._create_index(stripped)
        import re as _re

        m = _re.match(
            r"(?is)^set\s+(\w+)\s*=\s*'?(\w+)'?\s*;?\s*$", stripped
        )
        if m:
            # session variables (the reference's SET handler; the one
            # consumed today gates delta-join planning like
            # rw_streaming_enable_delta_join)
            var, val = m.group(1).lower(), m.group(2).lower()
            truthy = val in ("true", "on", "1", "yes")
            if var in ("enable_delta_join", "rw_streaming_enable_delta_join"):
                self.catalog.enable_delta_join = truthy
            elif var in ("batch_spill_threshold", "rw_batch_spill_threshold"):
                if val in ("off", "none", "0"):
                    self.batch.spill_threshold_rows = None
                elif val.isdigit():
                    self.batch.spill_threshold_rows = int(val)
                else:
                    raise ValueError(
                        f"batch_spill_threshold needs an integer or "
                        f"'off', got {val!r}"
                    )
            elif var in ("barrier_interval_ms", "checkpoint_frequency"):
                # cluster-mutable system params (the reference's ALTER
                # SYSTEM SET surface, system_param/mod.rs:78): take
                # effect at the next tick/barrier
                if not val.isdigit() or int(val) <= 0:
                    raise ValueError(f"{var} needs a positive integer")
                setattr(self.runtime, var, int(val))
            else:
                self.session_vars = getattr(self, "session_vars", {})
                self.session_vars[var] = val
            self._log_ddl(stripped)
            return {}, "SET"
        if stripped[:8].lower() == "explain ":
            from risingwave_tpu.sql.optimizer import explain_sql

            plan = explain_sql(stripped[8:], catalog=self.catalog)
            return {
                "QUERY PLAN": np.asarray(
                    plan.rstrip("\n").split("\n"), dtype=object
                )
            }, "EXPLAIN"
        stmt = P.parse(sql)
        if isinstance(stmt, P.CreateTable):
            if (
                stmt.name in self.catalog.tables
                or stmt.name in self.runtime.fragments
            ):
                raise ValueError(f"relation {stmt.name!r} already exists")
            fields = [
                _parse_type_word(cname, tword)
                for cname, tword in stmt.columns
            ]
            schema = Schema(fields)
            self.catalog.tables[stmt.name] = schema
            if stmt.watermark is not None:
                # WATERMARK FOR: MVs over this table get a self-driving
                # watermark filter at the scan (planner inserts it)
                self.catalog.watermarks[stmt.name] = stmt.watermark
            # a table IS a materialized relation (create_table.rs makes
            # the same plan: dml -> row-id gen -> materialize): give it
            # a fragment so INSERTs land somewhere queryable and
            # downstream MVs backfill from its snapshot
            from risingwave_tpu.array.composite import expand_field
            from risingwave_tpu.executors.materialize import (
                MaterializeExecutor,
            )
            from risingwave_tpu.executors.row_id_gen import RowIdGenExecutor
            from risingwave_tpu.runtime import Pipeline

            # composite columns (interval/struct/list) expand to their
            # leaf device lanes; the MV stores lanes, the result edge
            # reassembles values (array/composite.py)
            lane_names = tuple(
                ln for f in schema.fields for (ln, _) in expand_field(f)
            )
            if stmt.pk:
                # user pk: upsert table (create_table.rs pk handling) —
                # probe-able by temporal joins; no hidden row id.
                # conflict_resolve: a pk-conflicting INSERT emits
                # UpdateDelete(stored) + UpdateInsert(new) downstream,
                # so MVs over this table see real retractions
                # (materialize.rs:192-230 Overwrite)
                mview = MaterializeExecutor(
                    pk=stmt.pk,
                    columns=tuple(
                        ln for ln in lane_names if ln not in stmt.pk
                    ),
                    table_id=f"{stmt.name}.table",
                    conflict_resolve=True,
                )
                chain = [mview]
            else:
                mview = MaterializeExecutor(
                    pk=("_row_id",),
                    columns=lane_names,
                    table_id=f"{stmt.name}.table",
                )
                chain = [
                    RowIdGenExecutor(
                        out_col="_row_id",
                        table_id=f"{stmt.name}.rowid",
                    ),
                    mview,
                ]
            self.runtime.register(stmt.name, Pipeline(chain))
            self.batch.register(stmt.name, mview)
            self.dml.add_target(stmt.name, stmt.name, "single")
            self._log_ddl(sql)
            self._notify("add", "table", stmt.name, schema=schema, mview=mview)
            return {}, "CREATE_TABLE"
        return self._execute_create_mv_or_rest(stmt, sql)

    def _lint_planned(self, planned) -> None:
        """Static plan verification at CREATE-MV time (analysis/):
        findings land in ``self.lint_findings`` + metrics/event-log;
        with ``strict_lint``, errors raise PlanLintError and the DDL
        is refused with nothing registered."""
        from risingwave_tpu.analysis.lint import lint_planned

        # DDL-log replay must never be refused by lint: every statement
        # was accepted when first created, and a stricter rule added
        # since must not brick state recovery — record findings instead
        strict = self.strict_lint and not self._replaying
        for p in tuple(getattr(planned, "aux", ())) + (planned,):
            diags = lint_planned(p, catalog=self.catalog, strict=strict)
            self.lint_findings.extend((p.name, d) for d in diags)
            self._fusion_lint(p, strict=strict)
            self._mesh_lint(p, strict=strict)

    def _fusion_lint(self, planned, strict: bool) -> None:
        """Fusion-feasibility findings at CREATE-MV time (analysis/
        fusion_analyzer.py, shallow pass): STRICT BY DEFAULT now that
        the bucketing layer exists (runtime/bucketing.py) — RW-E803
        (unbucketed shape-polymorphic window, the class that wedges
        real TPUs) and RW-E806 (unsatisfiable declared lattice) refuse
        the DDL on window-keyed plans, same path as strict_lint; every
        built-in window-keyed executor declares a satisfiable lattice,
        so the Nexmark corpus walks free. RW_STRICT_FUSION=0 (env-only,
        like the other escape hatches) restores report-only mode —
        findings land in ``lint_findings`` as warnings."""
        import os

        from risingwave_tpu.analysis.diagnostics import PlanLintError
        from risingwave_tpu.analysis.lint import fusion_findings_for_ddl

        try:
            diags = fusion_findings_for_ddl(planned)
        except Exception:  # noqa: BLE001 — analysis must never brick DDL
            return
        if not diags:
            return
        self.lint_findings.extend((planned.name, d) for d in diags)
        strict_fusion = os.environ.get(
            "RW_STRICT_FUSION", "1"
        ).strip().lower() not in ("0", "off", "false", "")
        if strict and strict_fusion:
            raise PlanLintError(diags, name=planned.name)

    def _mesh_lint(self, planned, strict: bool) -> None:
        """Mesh-readiness findings at CREATE-MV time (analysis/
        mesh_analyzer.py, shallow pass): RW-E9xx SPMD-fusion blockers
        for plans carrying mesh-resident sharded executors. REPORT-ONLY
        by default — every sharded plan today has host-routed exchange
        edges by construction, so refusing on E9xx would refuse the
        whole sharded mode; findings land in ``lint_findings`` as
        warnings, same surface the CLI and tests read. RW_STRICT_MESH=1
        (env-only opt-in, the inverse default of RW_STRICT_FUSION)
        upgrades findings to DDL refusal for deployments that only
        accept proven-SPMD plans — replay-safe like every other lint:
        ``strict`` is already False during DDL-log replay."""
        import os

        from risingwave_tpu.analysis.diagnostics import PlanLintError
        from risingwave_tpu.analysis.lint import mesh_findings_for_ddl

        try:
            diags = mesh_findings_for_ddl(planned)
        except Exception:  # noqa: BLE001 — analysis must never brick DDL
            return
        if not diags:
            return
        self.lint_findings.extend((planned.name, d) for d in diags)
        strict_mesh = os.environ.get(
            "RW_STRICT_MESH", "0"
        ).strip().lower() in ("1", "on", "true", "yes")
        if strict and strict_mesh:
            raise PlanLintError(diags, name=planned.name)

    def _rollback_aux_catalog(self, planned) -> None:
        """The planner adds hidden aux entries to the catalog during
        lowering — a refused/failed CREATE must not leak them."""
        for sub in planned.aux:
            self.catalog.mvs.pop(sub.name, None)
            self.catalog.tables.pop(sub.name, None)

    def _discard_planned(self, planned) -> None:
        """Tear down a planned MV that will never launch (duplicate
        name, lint refusal, registration failure): roll back hidden aux
        catalog entries and reap graph-mode actor threads, which spawn
        at PLAN time. A wedged/dead graph must not mask the original
        error (GraphPipeline.rebuild guards its stop() identically)."""
        self._rollback_aux_catalog(planned)
        self._close_pipeline(planned.pipeline)

    @staticmethod
    def _close_pipeline(pipeline) -> None:
        """Guarded pipeline teardown (graph pipelines spawn actor
        threads at PLAN time): a wedged/dead graph must never mask the
        caller's real error or stall a DROP."""
        close = getattr(pipeline, "close", None)
        if close is not None:
            try:
                close()
            except BaseException:
                pass

    def _free_arrangement(self, arr) -> None:
        """Refcount hit zero: unregister the (possibly renamed) writer
        fragments, detach their DML routes, and reap actor threads —
        after this, the live-array census must be back to baseline."""
        for frag in arr.fragments:
            if frag in self.runtime.fragments:
                self.runtime.unregister(frag)
            self.dml.detach_fragment(frag)
        for sub in reversed(getattr(arr.planned, "aux", ())):
            self._close_pipeline(getattr(sub, "pipeline", None))
        self._close_pipeline(getattr(arr.planned, "pipeline", None))

    def _register_planned(self, planned) -> None:
        """Runtime-register one planned MV: subscribe fragment inputs
        (tables / MVs) with the correct join side + backfill; attach
        DML targets for raw base streams; expose to batch reads.
        Shared by top-level MVs and lowered-join aux MVs."""
        # an input that is an ATTACHED shared-MV name has no fragment
        # of its own: route the subscription to the arrangement's
        # writer fragment (whose emission is exactly the attached MV's
        # change stream)
        reg = getattr(self.runtime, "arrangements", None)
        alias = {}
        if reg is not None:
            for s in planned.inputs:
                real = reg.fragment_for(s)
                if real is not None:
                    alias[s] = real
                    # the dependency is logically on the attached NAME
                    # (the _subs edge will carry the writer fragment)
                    self._attached_deps.setdefault(s, set()).add(
                        planned.name
                    )
        frag_inputs = {
            alias.get(s, s): side
            for s, side in planned.inputs.items()
            if alias.get(s, s) in self.runtime.fragments
        }
        # a delta join's arrangements are PRE-POPULATED (shared with
        # CREATE INDEX): replaying both base snapshots through the join
        # would join existing data twice — seed from one arrangement
        # instead (see _seed_delta_join)
        delta = getattr(planned, "delta_join", False)
        self.runtime.register(planned.name, planned.pipeline)
        try:
            for s, side in frag_inputs.items():
                # replay restores state from checkpoints afterwards:
                # backfilling from empty uprights would double rows
                self.runtime.subscribe(
                    s,
                    planned.name,
                    side=side,
                    backfill=not self._replaying and not delta,
                )
        except BaseException:
            # keep the graph consistent on backfill failure: a
            # half-registered fragment would crash later barriers
            self.runtime.unregister(planned.name)
            raise
        if len(frag_inputs) < len(planned.inputs):
            self.dml.attach(planned, skip=frag_inputs.keys())
        self.batch.register(planned.name, planned.mview)
        if delta and not self._replaying:
            self._seed_delta_join(planned)

    def _seed_delta_join(self, planned) -> None:
        """Initial snapshot for a delta-join MV: replay the LEFT
        arrangement's current rows through apply_left (the right
        arrangement already holds all existing right rows, so this
        yields exactly A ⋈ B once)."""
        import numpy as np

        from risingwave_tpu.array.chunk import StreamChunk

        join = planned.pipeline.join
        arr = join.left_arr
        rows = list(arr.rows.items())
        names = arr.pk + arr.columns
        for at in range(0, len(rows), 512):
            part = rows[at : at + 512]
            cols: Dict[str, list] = {n: [] for n in names}
            for k, v in part:
                for n, val in zip(arr.pk, k):
                    cols[n].append(val)
                for n, val in zip(arr.columns, v):
                    cols[n].append(val)
            nulls = {
                n: np.asarray([v is None for v in vs], bool)
                for n, vs in cols.items()
                if any(v is None for v in vs)
            }
            npcols = {
                n: np.asarray(
                    [0 if v is None else v for v in vs], np.int64
                )
                for n, vs in cols.items()
            }
            cap = 1 << max(1, int(np.ceil(np.log2(max(2, len(part))))))
            self.runtime.push(
                planned.name,
                StreamChunk.from_numpy(npcols, cap, nulls=nulls),
                side="left",
            )

    def _unregister_planned(self, planned) -> None:
        """Undo EVERYTHING _register_planned did — stale DML targets
        or batch registrations pointing at an unregistered fragment
        would crash later INSERTs / serve half-built MVs."""
        self.runtime.unregister(planned.name)
        self.dml.detach_fragment(planned.name)
        self.batch.tables.pop(planned.name, None)
        self._drop_attached_dep(planned.name)

    def _drop_attached_dep(self, name: str) -> None:
        """``name`` is gone: it no longer depends on any attached MV."""
        for dep_of, deps in list(self._attached_deps.items()):
            deps.discard(name)
            if not deps:
                del self._attached_deps[dep_of]

    def _share_fingerprint(self, stmt):
        """The CREATE-MV share key (runtime/arrangements.py), or None
        when sharing is off / the statement is not share-eligible."""
        from risingwave_tpu.runtime.arrangements import (
            plan_share_fingerprint,
        )

        reg = getattr(self.runtime, "arrangements", None)
        if reg is None or not reg.enabled:
            return None, None
        fp = plan_share_fingerprint(
            stmt,
            self.catalog,
            capacity=self.capacity,
            exec_mode=self.exec_mode,
            parallelism=self.parallelism,
            # string literals encode against THIS session's dictionary:
            # sharing never crosses a dictionary boundary
            session_token=id(self.strings),
        )
        return reg, fp

    def _attach_shared(self, stmt, sql, arr, reg):
        """Registry HIT: bind the new MV name to the existing
        refcounted arrangement — no planning, no executors, no device
        state, no compiles. Reads serve off the per-barrier published
        version (snapshot-consistent by construction)."""
        name = stmt.name
        if (
            name in self.runtime.fragments
            or name in self.catalog.tables
        ):
            raise ValueError(f"relation {name!r} already exists")
        facade = reg.attach(arr, name)
        with self._registry_guard:
            self.catalog.tables[name] = arr.schema
            self.catalog.mvs[name] = _AttachedMV(name, arr, facade)
            self.batch.register(name, facade)
        self._log_ddl(sql)
        self._notify(
            "add", "mv", name, schema=arr.schema, mview=facade,
            planned=None,
        )
        if not self._replaying:
            # CREATE returns once a published version exists for the
            # new reader (the attach analogue of backfill visibility)
            self.runtime.barrier()
        return {}, "CREATE_MATERIALIZED_VIEW"

    def _execute_create_mv_or_rest(self, stmt, sql):
        if isinstance(stmt, P.CreateMaterializedView):
            is_union = isinstance(stmt.select, P.UnionAll)
            nested_join = not is_union and isinstance(
                stmt.select.from_, P.Join
            ) and (
                isinstance(stmt.select.from_.left, P.Join)
                or isinstance(stmt.select.from_.right, P.Join)
            )
            # shared arrangements: a structurally-identical live MV
            # already maintains this exact index — attach instead of
            # building (and compiling) a private twin
            reg, fp = self._share_fingerprint(stmt)
            if fp is not None:
                arr = reg.lookup(fp)
                if arr is not None:
                    return self._attach_shared(stmt, sql, arr, reg)
            if self.exec_mode == "graph" and not nested_join and not is_union:
                from risingwave_tpu.runtime.fragmenter import graph_planned_mv

                planned = graph_planned_mv(
                    self._fresh_planner, sql, parallelism=self.parallelism
                )
            else:
                # multi-way joins lower into a tree of hidden MVs
                # (planner aux) — serial registration path
                planned = self.planner.plan(sql)
            if planned.name in self.runtime.fragments:
                self._discard_planned(planned)
                raise ValueError(
                    f"relation {planned.name!r} already exists"
                )
            # rwlint: refuse a provably-broken dataflow BEFORE anything
            # registers (aux MVs included — deepest first, like
            # registration order)
            try:
                self._lint_planned(planned)
            except BaseException:
                self._discard_planned(planned)
                raise
            # register the lowered-join aux MVs first (deepest first):
            # the outer join subscribes to their change streams
            registered_aux = []
            try:
                for sub in planned.aux:
                    self._register_planned(sub)
                    registered_aux.append(sub)
                self._register_planned(planned)
            except BaseException:
                for sub in reversed(registered_aux):
                    self._unregister_planned(sub)
                self._discard_planned(planned)
                raise
            from risingwave_tpu.sql.typing import infer_output_fields

            with self._registry_guard:
                self.catalog.add_mv(planned)
                # overlay inferred LOGICAL types (decimal scale,
                # varchar, jsonb) over the MV's physical schema so
                # SELECTs over it decode correctly (sql/typing.py)
                inferred = infer_output_fields(stmt.select, self.catalog)
                sch = self.catalog.tables[planned.name]
                self.catalog.tables[planned.name] = Schema(
                    tuple(inferred.get(f.name, f) for f in sch.fields)
                )
            if fp is not None:
                # record the new MV as the share target for later
                # structurally-identical CREATEs
                reg.adopt(fp, planned, self.catalog.tables[planned.name])
            self._log_ddl(sql)
            self._notify(
                "add", "mv", planned.name,
                schema=self.catalog.tables[planned.name],
                mview=planned.mview, planned=planned,
            )
            if not self._replaying:
                # CREATE returns once the backfill snapshot is visible
                # (the reference blocks DDL on backfill completion)
                self.runtime.barrier()
            return {}, "CREATE_MATERIALIZED_VIEW"
        if isinstance(stmt, P.InsertValues):
            n = self.dml.execute(sql)
            # DML visibility: the reference commits DML at the next
            # checkpoint barrier; interactive sessions read their own
            # writes, so advance the barrier clock here
            self.runtime.barrier()
            return {}, f"INSERT 0 {n}"
        if isinstance(stmt, (P.DeleteFrom, P.UpdateSet)):
            n = self._execute_delete_update(stmt)
            self.runtime.barrier()
            verb = "DELETE" if isinstance(stmt, P.DeleteFrom) else "UPDATE"
            return {}, f"{verb} {n}"
        if isinstance(stmt, P.UnionAll):
            raise NotImplementedError(
                "ad-hoc UNION ALL queries are unsupported: CREATE a "
                "MATERIALIZED VIEW over the union and SELECT from it"
            )
        from risingwave_tpu.sql.typing import typecheck_select

        stmt = typecheck_select(stmt, self.catalog, self.strings)
        out = self.batch.query(sql, stmt=stmt)
        out = self._decode_output(stmt, out)
        n = len(next(iter(out.values()))) if out else 0
        return out, f"SELECT {n}"

    def _execute_delete_update(self, stmt) -> int:
        """DELETE FROM / UPDATE ... SET over a base table (reference:
        handler/dml.rs -> batch delete/update executors feeding the
        table's DML channel). The matching stored rows become a
        retraction chunk pushed through the table's own fragment, so
        the table state AND every subscribed MV converge together."""
        from risingwave_tpu.array.chunk import StreamChunk
        from risingwave_tpu.sql.planner import Binder, compile_scalar
        from risingwave_tpu.types import Op

        name = stmt.table
        if (
            name not in self.catalog.tables
            or self.catalog.is_mv(name)
            or name in self.sources
        ):
            raise ValueError(f"{name!r} is not a DML-writable table")
        mview = self.batch.tables.get(name)
        if mview is None or name not in self.runtime.fragments:
            raise KeyError(f"unknown table {name!r}")
        cols = mview.to_numpy()
        nrows = len(next(iter(cols.values()))) if cols else 0
        if nrows == 0:
            return 0
        schema = self.catalog.tables[name]
        sets = getattr(stmt, "sets", ())
        for c, _ in sets:
            if c not in schema.names:
                raise KeyError(f"unknown column {c!r}")
            if c in getattr(mview, "pk", ()):
                raise ValueError(
                    f"UPDATE of primary-key column {c!r} unsupported "
                    "(DELETE + INSERT instead)"
                )
        # type-directed literal rewriting (decimal scales, varchar
        # codes) through the SAME path SELECT uses: a synthetic select
        # carrying the WHERE + SET expressions
        items = [
            P.SelectItem(P.Ident(f.name), None) for f in schema.fields
        ] + [
            P.SelectItem(ex, f"__set{j}") for j, (_, ex) in enumerate(sets)
        ]
        sel = P.Select(
            items=tuple(items),
            from_=P.TableRef(name, None),
            where=stmt.where,
            group_by=(),
        )
        from risingwave_tpu.sql.typing import typecheck_select

        sel = typecheck_select(sel, self.catalog, self.strings)
        where = sel.where
        set_exprs = [
            (sets[j][0], sel.items[len(schema.fields) + j].expr)
            for j in range(len(sets))
        ]
        # stored lanes -> numpy (+ null masks out of object lanes)
        lanes: Dict[str, np.ndarray] = {}
        nulls_in: Dict[str, np.ndarray] = {}
        for k, v in cols.items():
            arr = np.asarray(v)
            if arr.dtype == object:
                vals = arr.tolist()
                nl = np.asarray([x is None for x in vals], bool)
                arr = np.asarray(
                    [0 if m else x for x, m in zip(vals, nl.tolist())]
                )
                if nl.any():
                    nulls_in[k] = nl
            lanes[k] = arr
        cap = max(2, 1 << (nrows - 1).bit_length())
        chunk = StreamChunk.from_numpy(lanes, cap, nulls=nulls_in or None)
        binder = Binder({k: v.dtype for k, v in lanes.items()}, None)
        if where is not None:
            kv, kn = compile_scalar(where, binder).eval(chunk)
            keep = np.asarray(kv).astype(bool)[:nrows]
            if kn is not None:
                keep &= ~np.asarray(kn)[:nrows]
        else:
            keep = np.ones(nrows, bool)
        m = int(keep.sum())
        if m == 0:
            return 0
        old_cols = {k: v[:nrows][keep] for k, v in lanes.items()}
        old_nulls = {
            k: v[:nrows][keep] for k, v in nulls_in.items()
        }
        if not sets:  # DELETE
            out = StreamChunk.from_numpy(
                old_cols,
                max(2, 1 << (m - 1).bit_length()),
                ops=np.full(m, int(Op.DELETE), np.int32),
                nulls=old_nulls or None,
            )
            self.runtime.push(name, out)
            return m
        # UPDATE: evaluate SET expressions over the full chunk, take
        # the kept rows, and interleave UpdateDelete(old)/
        # UpdateInsert(new) pairs
        new_cols = {k: v.copy() for k, v in old_cols.items()}
        new_nulls = {k: v.copy() for k, v in old_nulls.items()}
        for cname, ex in set_exprs:
            nv, nn = compile_scalar(ex, binder).eval(chunk)
            nv = np.asarray(nv)[:nrows][keep]
            tgt = lanes[cname].dtype
            # the INSERT path's overflow guard (chunk.py from_numpy)
            # must hold here too: never silently wrap/truncate
            if np.issubdtype(tgt, np.integer) and nv.size:
                if np.issubdtype(nv.dtype, np.floating):
                    if not np.all(np.mod(nv, 1) == 0):
                        raise ValueError(
                            f"UPDATE value for {cname!r} is not integral"
                        )
                info = np.iinfo(tgt)
                live = (
                    ~np.asarray(nn)[:nrows][keep]
                    if nn is not None
                    else np.ones(m, bool)
                )
                if np.any((nv[live] < info.min) | (nv[live] > info.max)):
                    raise ValueError(
                        f"UPDATE value overflows column {cname!r} "
                        f"dtype {tgt}"
                    )
            new_cols[cname] = nv.astype(tgt, copy=False)
            nn_host = (
                np.asarray(nn)[:nrows][keep]
                if nn is not None
                else np.zeros(m, bool)
            )
            if nn_host.any():
                new_nulls[cname] = nn_host
            else:
                new_nulls.pop(cname, None)
        inter_cols = {}
        inter_nulls = {}
        for k in old_cols:
            merged = np.empty(2 * m, old_cols[k].dtype)
            merged[0::2] = old_cols[k]
            merged[1::2] = new_cols[k]
            inter_cols[k] = merged
            onl = old_nulls.get(k)
            nnl = new_nulls.get(k)
            if onl is not None or nnl is not None:
                mn = np.zeros(2 * m, bool)
                if onl is not None:
                    mn[0::2] = onl
                if nnl is not None:
                    mn[1::2] = nnl
                inter_nulls[k] = mn
        ops = np.empty(2 * m, np.int32)
        ops[0::2] = int(Op.UPDATE_DELETE)
        ops[1::2] = int(Op.UPDATE_INSERT)
        out_cap = max(2, 1 << (2 * m - 1).bit_length())
        out = StreamChunk.from_numpy(
            inter_cols, out_cap, ops=ops, nulls=inter_nulls or None
        )
        self.runtime.push(name, out)
        return m

    def _register_string_builtins(self) -> None:
        """Dictionary-backed string functions (reference: the string
        half of src/expr/impl/src/scalar/). VARCHAR lanes carry codes,
        so these run host-side through the same typed-callback path as
        python UDFs, decode -> op -> encode against THIS session's
        dictionary — always-fresh against dictionary growth (a baked
        code->code gather table would go stale inside jitted programs;
        expr.functions.StringFunc offers that faster form for
        fixed-dictionary Python-API pipelines). Registered PROTECTED:
        CREATE/DROP FUNCTION cannot shadow or remove them. The
        registry is process-global, so the LATEST session's dictionary
        wins — one live SQL session per process is the contract (the
        reference scopes functions per cluster the same way)."""
        from risingwave_tpu.expr import functions as F

        def _substr(s, start, n):
            # PostgreSQL substr: positions are 1-based; a non-positive
            # start consumes length; negative length is an error
            if n < 0:
                raise ValueError("negative substring length")
            a, b = max(start, 1), max(start + n, 1)
            return s[a - 1 : b - 1]

        def _split_part(s, d, n):
            if n == 0:
                raise ValueError("split_part field position must not be 0")
            parts = s.split(d) if d else [s]
            i = n - 1 if n > 0 else len(parts) + n
            return parts[i] if 0 <= i < len(parts) else ""

        def _overlay(s, repl, start, n):
            a = max(start - 1, 0)
            return s[:a] + repl + s[a + n :]

        def _md5(s):
            import hashlib

            return hashlib.md5(s.encode()).hexdigest()

        B = Field("b", DataType.BOOLEAN)
        V = Field("s", DataType.VARCHAR)
        I = Field("n", DataType.INT64)
        sigs = {
            "length": (I, (V,), lambda s: len(s)),
            "upper": (V, (V,), lambda s: s.upper()),
            "lower": (V, (V,), lambda s: s.lower()),
            "trim": (V, (V,), lambda s: s.strip(" ")),  # PG trim: spaces only
            "ltrim": (V, (V,), lambda s: s.lstrip(" ")),
            "rtrim": (V, (V,), lambda s: s.rstrip(" ")),
            "btrim": (V, (V, V), lambda s, cs: s.strip(cs)),
            "reverse": (V, (V,), lambda s: s[::-1]),
            "concat": (V, (V, V), lambda a, b: a + b),
            "concat_ws": (
                V, (V, V, V), lambda sep, a, b: sep.join((a, b)),
            ),
            "substr": (V, (V, I, I), _substr),
            "replace": (V, (V, V, V), lambda s, a, b: s.replace(a, b)),
            "starts_with": (B, (V, V), lambda s, p: s.startswith(p)),
            "ends_with": (B, (V, V), lambda s, p: s.endswith(p)),
            "char_length": (I, (V,), lambda s: len(s)),
            "position": (I, (V, V), lambda sub, s: s.find(sub) + 1),
            "strpos": (I, (V, V), lambda s, sub: s.find(sub) + 1),
            "repeat": (V, (V, I), lambda s, n: s * max(n, 0)),
            "initcap": (V, (V,), lambda s: s.title()),
            "left": (V, (V, I), lambda s, n: s[:n] if n >= 0 else s[: len(s) + n]),
            "right": (V, (V, I), lambda s, n: s[-n:] if n > 0 else s[-n if n else len(s):]),
            "lpad": (V, (V, I, V), lambda s, n, p: s[:n] if len(s) >= n else (p * n)[: n - len(s)] + s),
            "rpad": (V, (V, I, V), lambda s, n, p: s[:n] if len(s) >= n else s + (p * n)[: n - len(s)]),
            "split_part": (V, (V, V, I), _split_part),
            "translate": (
                V, (V, V, V),
                lambda s, frm, to: s.translate(
                    {ord(c): (to[i] if i < len(to) else None)
                     for i, c in enumerate(frm)}
                ),
            ),
            "overlay": (V, (V, V, I, I), _overlay),
            "md5": (V, (V,), _md5),
            "ascii": (I, (V,), lambda s: ord(s[0]) if s else 0),
            "chr": (V, (I,), lambda n: chr(n)),
        }
        for name, (out, args, fn) in sigs.items():
            F.register_py_udf(
                name, fn, out, list(args),
                strings=self.strings, protected=True,
            )

    def _create_index(self, sql: str):
        """CREATE INDEX name ON table (col [, ...]) — an index IS a
        special MV (the reference plans it the same way,
        handler/create_index.rs): an IndexArrangement keyed by the
        index columns ‖ base pk, maintained from the base change
        stream, backfilled from the base snapshot, and shared by
        delta-join plans."""
        import re

        from risingwave_tpu.executors.lookup import IndexArrangement
        from risingwave_tpu.runtime import Pipeline

        m = re.match(
            r"(?is)^create\s+index\s+(\w+)\s+on\s+(\w+)\s*"
            r"\(([^)]+)\)\s*;?\s*$",
            sql,
        )
        if not m:
            raise SyntaxError("CREATE INDEX <name> ON <table> (cols...)")
        name, base, colraw = m.group(1), m.group(2), m.group(3)
        cols = tuple(c.strip() for c in colraw.split(","))
        if name in self.catalog.indexes or name in self.runtime.fragments:
            raise ValueError(f"relation {name!r} already exists")
        if base not in self.runtime.fragments:
            raise KeyError(f"unknown base relation {base!r}")
        base_mv = self.batch.tables.get(base)
        if base_mv is None:
            raise KeyError(f"base relation {base!r} is not materialized")
        base_pk = tuple(base_mv.pk)
        base_cols = tuple(base_mv.pk) + tuple(base_mv.columns)
        for c in cols:
            if c not in base_cols:
                raise KeyError(f"column {c!r} not in {base!r}")
        rest = tuple(
            c for c in base_cols if c not in cols and c not in base_pk
        )
        arr = IndexArrangement(
            index_cols=cols,
            base_pk=base_pk,
            columns=rest,
            table_id=f"{name}.index",
        )
        self.runtime.register(name, Pipeline([arr]))
        try:
            self.runtime.subscribe(
                base, name, backfill=not self._replaying
            )
        except BaseException:
            self.runtime.unregister(name)
            raise
        self.catalog.indexes[name] = {
            "base": base,
            "cols": cols,
            "base_pk": base_pk,
            "arrangement": arr,
        }
        self.batch.register(name, arr)
        self._log_ddl(sql)
        return {}, "CREATE_INDEX"

    def _create_source(self, sql: str):
        """CREATE SOURCE name (cols) WITH (connector='filelog'|'datagen',
        ... , format='json'|'csv') — external ingestion through the
        connector framework (reference: handler/create_source.rs +
        src/connector/). MVs FROM the source get its polled chunks via
        ``pump_sources`` (the CLI clock calls it every tick)."""
        import re

        from risingwave_tpu.connectors.framework import (
            CsvParser,
            DatagenSource,
            FileLogSource,
            GenericSourceExecutor,
            JsonParser,
        )

        m = re.match(
            r"(?is)^create\s+source\s+(\w+)\s*\((.*?)\)\s*"
            r"with\s*\((.*?)\)\s*;?\s*$",
            sql,
        )
        if not m:
            raise SyntaxError(
                "CREATE SOURCE name (col TYPE, ...) WITH (connector=..., "
                "format=...)"
            )
        name, cols, props_raw = m.groups()
        if name in self.catalog.tables:
            raise ValueError(f"relation {name!r} already exists")
        props = {}
        for kv in re.findall(r"(\w+)\s*=\s*'([^']*)'", props_raw):
            props[kv[0].lower()] = kv[1]
        fields = []
        watermark = None
        # split on commas OUTSIDE parens: DECIMAL(10,2) is one type
        for c in re.split(r",(?![^(]*\))", cols):
            c = c.strip()
            if not c:
                continue
            wm = re.match(
                r"(?is)^watermark\s+for\s+(\w+)\s+as\s+(\w+)\s*-\s*"
                r"interval\s+'(\d+)(?:\s+(\w+))?'\s*(\w+)?\s*$",
                c,
            )
            if wm:
                from risingwave_tpu.sql.parser import INTERVAL_SCALES

                # SQL identifiers fold case-insensitively (the Parser
                # path lowercases in the lexer)
                if wm.group(1).lower() != wm.group(2).lower():
                    raise SyntaxError(
                        "WATERMARK expression must be <col> - INTERVAL"
                    )
                unit = (wm.group(5) or wm.group(4) or "second").lower()
                scale = INTERVAL_SCALES.get(unit)
                if scale is None:
                    raise SyntaxError(f"bad interval unit {unit!r}")
                watermark = (
                    wm.group(1).lower(),
                    int(wm.group(3)) * scale,
                )
                continue
            parts = c.split(None, 1)
            if len(parts) != 2:
                raise SyntaxError(f"column {c!r}: expected 'name TYPE'")
            fields.append(
                _parse_type_word(parts[0], parts[1].replace(" ", ""))
            )
        schema = Schema(fields)
        if watermark is not None and watermark[0] not in {
            f.name for f in fields
        }:
            raise SyntaxError(
                f"WATERMARK over unknown column {watermark[0]!r}"
            )
        kind = props.get("connector")
        if kind == "filelog":
            conn = FileLogSource(props["path"])
        elif kind == "datagen":
            conn = DatagenSource(
                schema, split_num=int(props.get("split_num", "1"))
            )
        else:
            raise ValueError(f"unknown connector {kind!r}")
        fmt = props.get("format", "json")
        if fmt == "json":
            parser = JsonParser(schema)
        elif fmt == "csv":
            parser = CsvParser(schema)
        elif fmt == "debezium":
            # Debezium CDC envelopes: op r/c -> insert, u -> retract +
            # reinsert, d -> delete (reference FORMAT DEBEZIUM,
            # src/connector/src/parser/debezium/)
            from risingwave_tpu.connectors.framework import (
                DebeziumJsonParser,
            )

            parser = DebeziumJsonParser(schema)
        elif fmt == "upsert_json":
            from risingwave_tpu.connectors.framework import (
                UpsertJsonParser,
            )

            parser = UpsertJsonParser(schema)
        elif fmt == "avro":
            from risingwave_tpu.connectors.avro import AvroParser

            if "avro_schema" not in props:
                raise ValueError(
                    "format='avro' needs avro_schema='...' in WITH (...)"
                )
            parser = AvroParser(
                schema,
                props["avro_schema"],
                registry_framed=props.get("registry_framed", "")
                .lower() in ("true", "t", "1"),
            )
        else:
            raise ValueError(f"unknown source format {fmt!r}")
        src = GenericSourceExecutor(
            conn, parser, table_id=f"{name}.source", strings=self.strings
        )
        self.sources[name] = src
        self.source_mgr.register(name, src, parallelism=self.parallelism)
        self.catalog.tables[name] = schema
        if watermark is not None:
            self.catalog.watermarks[name] = watermark
        self.runtime.register_state(src)
        self._log_ddl(sql)
        self._notify("add", "source", name, schema=schema, src=src)
        return {}, "CREATE_SOURCE"

    def pump_sources(
        self, max_rows_per_split: int = 4096, capacity: int = 1 << 12
    ) -> int:
        """Poll every source once and route chunks into the consuming
        fragments (the source executor's stream loop, driven by the
        host clock). Returns rows ingested."""
        total = 0
        with self.runtime.lock:
            for name, src in self.sources.items():
                if not self.dml._targets.get(name):
                    # no consumer yet: polling would advance offsets and
                    # permanently drop rows read before the first MV
                    continue
                # periodic discovery + least-loaded assignment of new
                # splits (source_manager.rs discovery loop); polling
                # walks each worker slot's DISJOINT split subset.
                # Worker order ROTATES per pump: under a rate limit the
                # slots share one token bucket, and a fixed order would
                # let slot 0 drain it every time (starving slot 1+ just
                # like an unrotated split order would)
                self.source_mgr.discover(name)
                par = self.source_mgr.parallelism(name)
                self._pump_rr = getattr(self, "_pump_rr", 0) + 1
                for w in (
                    (i + self._pump_rr) % par for i in range(par)
                ):
                    for chunk in self.source_mgr.poll(
                        name, w, max_rows_per_split, capacity
                    ):
                        total += int(np.asarray(chunk.valid).sum())
                        for frag, side in self.dml._targets.get(name, ()):
                            self.runtime.push(frag, chunk, side)
        return total

    def _execute_drop(self, sql: str):
        """DROP MATERIALIZED VIEW / TABLE / SOURCE <name> (reference:
        handler/drop_mv.rs etc. -> DdlController::drop_streaming_job).
        Dependency-guarded: a relation with downstream subscribers or
        DML-fed MVs refuses to drop (the reference requires CASCADE)."""
        import re

        m = re.match(
            r"(?is)^drop\s+(materialized\s+view|table|source)\s+"
            r"(\w+)\s*;?\s*$",
            sql,
        )
        if not m:
            raise SyntaxError("DROP MATERIALIZED VIEW|TABLE|SOURCE <name>")
        kword, name = m.group(1).lower(), m.group(2)
        if name.startswith("rw_"):
            # system tables (sys_tables.py) are read-only and reserved
            raise ValueError(f"cannot drop system table {name!r}")
        kind = {"materialized view": "mv"}.get(
            " ".join(kword.split()), kword
        )
        if kind == "mv":
            if not self.catalog.is_mv(name):
                raise KeyError(f"unknown materialized view {name!r}")
        elif kind == "table":
            if name not in self.catalog.tables or self.catalog.is_mv(
                name
            ) or name in self.sources:
                raise KeyError(f"unknown table {name!r}")
        else:
            if name not in self.sources:
                raise KeyError(f"unknown source {name!r}")
        # dependency guard: subscribers (MV-on-MV / MVs over the table)
        # or DML-attached MVs reading a source. An arrangement OWNER
        # with other references is exempt: its drop HANDS the fragment
        # off to an internal alias (subscription edges re-key with the
        # rename), so dependents keep their dataflow
        will_handoff = (
            kind == "mv"
            and (arr := self.runtime.arrangements._by_name.get(name))
            is not None
            and len(arr.refs) > 1
        )
        if self.runtime._subs.get(name) and not will_handoff:
            deps = [d for d, _ in self.runtime._subs[name]]
            raise ValueError(
                f"cannot drop {name!r}: {deps} depend on it"
            )
        # MVs built over an ATTACHED shared MV subscribe to the writer
        # fragment, so _subs never carries the attached name — the
        # alias-dependency map holds its dependents
        if self._attached_deps.get(name):
            raise ValueError(
                f"cannot drop {name!r}: "
                f"{sorted(self._attached_deps[name])} depend on it"
            )
        if kind == "source" and self.dml._targets.get(name):
            deps = [f for f, _ in self.dml._targets[name]]
            raise ValueError(
                f"cannot drop {name!r}: {deps} depend on it"
            )
        if kind == "mv":
            # dependency guard for arrangement-backed MVs: freeing the
            # LAST reference tears the writer fragment down, so any
            # MV-on-MV subscribed to that fragment (possibly through an
            # attached alias of it) blocks the drop — same contract as
            # the plain `_subs` guard above, which only sees the
            # user-visible name
            arr = self.runtime.arrangements._by_name.get(name)
            if arr is not None and len(arr.refs) == 1:
                deps = [
                    d
                    for frag in arr.fragments
                    for d, _ in self.runtime._subs.get(frag, ())
                ]
                if deps:
                    raise ValueError(
                        f"cannot drop {name!r}: {deps} depend on it"
                    )
            res = self.runtime.arrangements.detach(name)
            if res.kind in ("subscriber", "subscriber_free"):
                with self._registry_guard:
                    self.catalog.mvs.pop(name, None)
                    self.catalog.tables.pop(name, None)
                    self.batch.tables.pop(name, None)
                if res.kind == "subscriber_free":
                    # the LAST reference was a reader and the owner is
                    # long gone: the hidden writer tears down now —
                    # the refcount-zero free
                    self._free_arrangement(res.arrangement)
            elif res.kind == "handoff":
                # owner dropped with live subscribers: the writer keeps
                # streaming under the registry's internal alias; only
                # the user-visible name (and its now-stale aux catalog
                # entries) free up
                planned = self.catalog.mvs.pop(name)
                for old, new in res.renames:
                    self.dml.rename_fragment(old, new)
                with self._registry_guard:
                    self.catalog.tables.pop(name, None)
                    self.batch.tables.pop(name, None)
                    for sub in reversed(getattr(planned, "aux", ())):
                        self.batch.tables.pop(sub.name, None)
                        self.catalog.tables.pop(sub.name, None)
                        self.catalog.mvs.pop(sub.name, None)
            else:
                planned = self.catalog.mvs.pop(name)
                self.runtime.unregister(name)
                self.dml.detach_fragment(name)
                with self._registry_guard:
                    self.batch.tables.pop(name, None)
                    self.catalog.tables.pop(name, None)
                # hidden aux MVs (lowered joins) die with their top MV
                # unless another MV still subscribes to them
                for sub in reversed(getattr(planned, "aux", ())):
                    if self.runtime._subs.get(sub.name):
                        continue
                    self.runtime.unregister(sub.name)
                    self.dml.detach_fragment(sub.name)
                    with self._registry_guard:
                        self.batch.tables.pop(sub.name, None)
                        self.catalog.tables.pop(sub.name, None)
                        self.catalog.mvs.pop(sub.name, None)
                    self._close_pipeline(getattr(sub, "pipeline", None))
                # device-state leak fix: a dropped graph-mode MV used
                # to leave its actor threads alive, and the threads
                # kept every executor (and its HBM slabs) reachable —
                # the live-array census never returned to baseline.
                # Reap them with the same guarded close the discard
                # path uses.
                self._close_pipeline(getattr(planned, "pipeline", None))
        elif kind == "table":
            self.runtime.unregister(name)
            self.dml.detach_fragment(name)
            self.batch.tables.pop(name, None)
            self.catalog.tables.pop(name, None)
            self.catalog.watermarks.pop(name, None)
        else:  # source
            src = self.sources.pop(name, None)
            self.source_mgr.unregister(name)
            self.catalog.tables.pop(name, None)
            self.catalog.watermarks.pop(name, None)
            if src is not None:
                self.runtime.unregister_state(src)
        # the dropped relation no longer depends on any attached MV
        self._drop_attached_dep(name)
        self._log_ddl(sql)
        self._notify("drop", kind, name)
        return {}, f"DROP_{kind.upper()}"

    @staticmethod
    def _parse_udf_args(args: str):
        import re

        fields = []
        # split on commas OUTSIDE parens: DECIMAL(10,2) is one type
        for a in re.split(r",(?![^(]*\))", args):
            a = a.strip()
            if not a:
                continue
            parts = a.split(None, 1)
            if len(parts) != 2:
                raise SyntaxError(f"argument {a!r}: expected 'name TYPE'")
            fields.append(
                _parse_type_word(parts[0], parts[1].replace(" ", ""))
            )
        return fields

    def _create_function(self, sql: str):
        """CREATE FUNCTION name(args) RETURNS type LANGUAGE python AS
        $$ <python source defining def name(...) > $$ — the embedded
        python UDF surface (reference: src/expr/impl/src/udf/python.rs,
        handler/create_function.rs). The body runs host-side through
        jax.pure_callback inside jitted expression programs."""
        import re

        from risingwave_tpu.expr import functions as F

        ext = re.match(
            r"(?is)^create\s+function\s+(\w+)\s*\((.*?)\)\s*"
            r"returns\s+(\w+(?:\([\d\s,]*\))?)\s*"
            r"language\s+external\s+as\s+'([^']+)'\s*;?\s*$",
            sql,
        )
        if ext:
            # out-of-process UDF service (udf/external.rs analogue):
            # the body lives in a separate process at this address
            name, args, ret, address = ext.groups()
            arg_fields = self._parse_udf_args(args)
            F.register_external_udf(
                name,
                address,
                _parse_type_word("__ret__", ret),
                arg_fields,
                strings=self.strings,
            )
            self._log_ddl(sql)
            return {}, "CREATE_FUNCTION"
        m = re.match(
            r"(?is)^create\s+function\s+(\w+)\s*\((.*?)\)\s*"
            r"returns\s+(\w+(?:\([\d\s,]*\))?)\s*"
            r"language\s+python\s+as\s+\$\$(.*)\$\$\s*;?\s*$",
            sql,
        )
        if not m:
            raise SyntaxError(
                "CREATE FUNCTION name(arg TYPE, ...) RETURNS TYPE "
                "LANGUAGE python AS $$ def name(...): ... $$ | "
                "LANGUAGE external AS '<host:port>'"
            )
        name, args, ret, body = m.groups()
        arg_fields = self._parse_udf_args(args)
        ret_field = _parse_type_word("__ret__", ret)
        ns: Dict[str, object] = {}
        exec(body, ns)  # noqa: S102 — embedded UDFs run user code by design
        fn = ns.get(name)
        if not callable(fn):
            raise ValueError(
                f"UDF body must define a python function named {name!r}"
            )
        F.register_py_udf(
            name, fn, ret_field, arg_fields, strings=self.strings
        )
        self._log_ddl(sql)
        return {}, "CREATE_FUNCTION"

    def _decode_output(self, stmt, out):
        """Decode device lanes back to SQL values at the result edge:
        DECIMAL scaled ints -> Decimal, VARCHAR/JSONB dictionary codes
        -> strings/objects. Columns with no inferred logical type (or
        plain numerics) pass through raw."""
        from risingwave_tpu.array.composite import decode_column
        from risingwave_tpu.sql.typing import infer_output_fields

        fields = infer_output_fields(stmt, self.catalog)
        decoded = {}
        for name, arr in out.items():
            if name.endswith("__null"):
                continue
            f = fields.get(name)
            if f is not None and f.dtype in (
                DataType.DECIMAL,
                DataType.VARCHAR,
                DataType.JSONB,
            ):
                nl = out.get(name + "__null")
                raw = np.asarray(arr)
                if raw.dtype == object:
                    # python-backend MVs surface NULL as embedded None
                    vals = raw.tolist()
                    embedded = np.asarray([v is None for v in vals], bool)
                    nl = embedded if nl is None else (np.asarray(nl) | embedded)
                    raw = np.asarray(
                        [0 if v is None else v for v in vals]
                    )
                elif np.issubdtype(raw.dtype, np.floating):
                    # batch outer joins surface missing rows as NaN in
                    # float lanes; casting NaN to int64 would decode as
                    # garbage (INT64_MIN-scaled Decimals) instead of NULL
                    nan = np.isnan(raw)
                    if nan.any():
                        nl = nan if nl is None else (np.asarray(nl) | nan)
                        raw = np.where(nan, 0, raw)
                decoded[name] = np.asarray(
                    decode_column(
                        Field(name, f.dtype, scale=f.scale),
                        {name: raw.astype(f.dtype.device_dtype)},
                        lambda _ln: nl,
                        self.strings,
                    ),
                    dtype=object,
                )
            else:
                decoded[name] = arr
                nl = out.get(name + "__null")
                if nl is not None:
                    decoded[name] = np.asarray(
                        [
                            None if m else v
                            for v, m in zip(np.asarray(arr).tolist(), nl)
                        ],
                        dtype=object,
                    )
        return decoded
