"""pgwire — the Postgres wire protocol (v3) server.

Reference: src/utils/pgwire/src/pg_server.rs:250 (+ pg_protocol.rs
message codec): startup handshake, cleartext-free auth OK, the simple
query cycle Q -> RowDescription/DataRow*/CommandComplete ->
ReadyForQuery, ErrorResponse on failure, SSLRequest politely refused.
Enough protocol for psql / psycopg simple queries to work against the
SqlSession.

This is a host control-plane surface — no device work happens here, so
a plain threaded TCP server (one thread per connection, like the
reference's per-session task) is the right shape.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Optional

import numpy as np

from risingwave_tpu.frontend.session import SqlSession

_SSL_REQUEST = 80877103
_CANCEL_REQUEST = 80877102

# type OIDs (pg catalog)
_OID_BOOL, _OID_INT8, _OID_FLOAT8, _OID_TEXT = 16, 20, 701, 25


def _oid_of(dtype: np.dtype) -> int:
    if dtype == np.bool_:
        return _OID_BOOL
    if np.issubdtype(dtype, np.integer):
        return _OID_INT8
    if np.issubdtype(dtype, np.floating):
        return _OID_FLOAT8
    return _OID_TEXT


def _msg(tag: bytes, payload: bytes = b"") -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


class _Conn(socketserver.BaseRequestHandler):
    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            got = self.request.recv(n - len(buf))
            if not got:
                return None
            buf += got
        return buf

    def _startup(self) -> bool:
        while True:
            head = self._recv_exact(8)
            if head is None:
                return False
            length, code = struct.unpack("!II", head)
            body = self._recv_exact(length - 8)
            if body is None:
                return False
            if code == _SSL_REQUEST:
                self.request.sendall(b"N")  # no TLS; client retries plain
                continue
            if code == _CANCEL_REQUEST:
                return False
            # normal StartupMessage (protocol 3.0) — params ignored
            return True

    def handle(self):
        if not self._startup():
            return
        out = self.request.sendall
        out(_msg(b"R", struct.pack("!I", 0)))  # AuthenticationOk
        for k, v in (
            ("server_version", "13.0 (risingwave-tpu)"),
            ("client_encoding", "UTF8"),
        ):
            out(_msg(b"S", k.encode() + b"\0" + v.encode() + b"\0"))
        out(_msg(b"K", struct.pack("!II", 0, 0)))  # BackendKeyData
        out(_msg(b"Z", b"I"))

        session: SqlSession = self.server.session  # type: ignore[attr-defined]
        while True:
            head = self._recv_exact(5)
            if head is None:
                return
            tag, length = head[:1], struct.unpack("!I", head[1:])[0]
            body = self._recv_exact(length - 4)
            if body is None:
                return
            if tag == b"X":  # Terminate
                return
            if tag != b"Q":  # only the simple query protocol
                out(
                    _err(f"unsupported message {tag!r}")
                    + _msg(b"Z", b"I")
                )
                continue
            sql = body.rstrip(b"\0").decode()
            try:
                with self.server.lock:  # type: ignore[attr-defined]
                    cols, tag_str = session.execute(sql)
                if cols:
                    names = list(cols)
                    fields = b""
                    for name in names:
                        fields += (
                            name.encode() + b"\0"
                            + struct.pack(
                                "!IhIhih",
                                0, 0, _oid_of(cols[name].dtype), -1, -1, 0,
                            )
                        )
                    out(
                        _msg(
                            b"T",
                            struct.pack("!h", len(names)) + fields,
                        )
                    )
                    n = len(cols[names[0]])
                    for i in range(n):
                        row = b""
                        for name in names:
                            v = cols[name][i]
                            if v is None or (
                                isinstance(v, float) and np.isnan(v)
                            ):
                                row += struct.pack("!i", -1)
                            else:
                                s = str(
                                    v.item() if hasattr(v, "item") else v
                                ).encode()
                                row += struct.pack("!i", len(s)) + s
                        out(
                            _msg(
                                b"D",
                                struct.pack("!h", len(names)) + row,
                            )
                        )
                out(_msg(b"C", tag_str.encode() + b"\0"))
            except Exception as e:  # noqa: BLE001 — surface as pg error
                out(_err(str(e)))
            out(_msg(b"Z", b"I"))


def _err(message: str) -> bytes:
    payload = (
        b"SERROR\0"
        + b"CXX000\0"
        + b"M" + message.encode() + b"\0"
        + b"\0"
    )
    return _msg(b"E", payload)


class PgServer:
    """Serve a SqlSession over pgwire on 127.0.0.1."""

    def __init__(self, session: SqlSession, port: int = 0):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Srv(("127.0.0.1", port), _Conn)
        self._srv.session = session  # type: ignore[attr-defined]
        self._srv.lock = threading.Lock()  # type: ignore[attr-defined]
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )

    def start(self) -> "PgServer":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
