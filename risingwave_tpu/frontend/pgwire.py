"""pgwire — the Postgres wire protocol (v3) server.

Reference: src/utils/pgwire/src/pg_server.rs:250 (+ pg_protocol.rs
message codec): startup handshake, cleartext-free auth OK, the simple
query cycle Q -> RowDescription/DataRow*/CommandComplete ->
ReadyForQuery, plus the EXTENDED protocol (Parse/Bind/Describe/
Execute/Close/Sync with text-format parameters — prepared statements
bind $n placeholders as SQL literals; Describe infers the row shape
from the typing layer without executing). ErrorResponse on failure,
SSLRequest politely refused. Enough protocol for psql / psycopg
simple AND extended queries to work against the SqlSession.

This is a host control-plane surface — no device work happens here, so
a plain threaded TCP server (one thread per connection, like the
reference's per-session task) is the right shape.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Optional

import numpy as np

from risingwave_tpu.frontend.session import SqlSession

_SSL_REQUEST = 80877103
_CANCEL_REQUEST = 80877102

# type OIDs (pg catalog)
_OID_BOOL, _OID_INT8, _OID_FLOAT8, _OID_TEXT = 16, 20, 701, 25


def _oid_of(dtype: np.dtype) -> int:
    if dtype == np.bool_:
        return _OID_BOOL
    if np.issubdtype(dtype, np.integer):
        return _OID_INT8
    if np.issubdtype(dtype, np.floating):
        return _OID_FLOAT8
    return _OID_TEXT


def _msg(tag: bytes, payload: bytes = b"") -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


class _Conn(socketserver.BaseRequestHandler):
    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            got = self.request.recv(n - len(buf))
            if not got:
                return None
            buf += got
        return buf

    def _startup(self) -> bool:
        while True:
            head = self._recv_exact(8)
            if head is None:
                return False
            length, code = struct.unpack("!II", head)
            body = self._recv_exact(length - 8)
            if body is None:
                return False
            if code == _SSL_REQUEST:
                self.request.sendall(b"N")  # no TLS; client retries plain
                continue
            if code == _CANCEL_REQUEST:
                return False
            # normal StartupMessage (protocol 3.0) — params ignored
            return True

    @staticmethod
    def _row_description(cols) -> bytes:
        names = list(cols)
        fields = b""
        for name in names:
            fields += (
                name.encode() + b"\0"
                + struct.pack(
                    "!IhIhih",
                    0, 0, _oid_of(np.asarray(cols[name]).dtype), -1, -1, 0,
                )
            )
        return _msg(b"T", struct.pack("!h", len(names)) + fields)

    @staticmethod
    def _data_rows(cols) -> bytes:
        names = list(cols)
        out = b""
        n = len(cols[names[0]]) if names else 0
        for i in range(n):
            row = b""
            for name in names:
                v = cols[name][i]
                if v is None or (isinstance(v, float) and np.isnan(v)):
                    row += struct.pack("!i", -1)
                else:
                    s = str(
                        v.item() if hasattr(v, "item") else v
                    ).encode()
                    row += struct.pack("!i", len(s)) + s
            out += _msg(b"D", struct.pack("!h", len(names)) + row)
        return out

    @staticmethod
    def _bind_params(sql: str, params) -> str:
        """Substitute $n placeholders as SQL literals (text-format
        extended protocol; the in-process prepared-statement form).
        SINGLE-PASS regex substitution: replacements are never
        rescanned, so a parameter whose VALUE contains '$k' text can
        never have another parameter spliced into it."""
        import re as _re

        def lit(m):
            i = int(m.group(1))
            if not 1 <= i <= len(params):
                raise KeyError(f"no parameter ${i}")
            p = params[i - 1]
            if p is None:
                return "NULL"
            s = p.decode()
            if _re.fullmatch(
                r"[+-]?(\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?", s
            ):
                return s
            return "'" + s.replace("'", "''") + "'"

        return _re.sub(r"\$(\d+)", lit, sql)

    def handle(self):
        # protocol turns are many small writes (RowDescription, rows,
        # CommandComplete, ReadyForQuery): with Nagle armed they batch
        # behind the peer's delayed ACK — a flat ~40ms floor on every
        # query. Serving-tier readers need the real latency.
        self.request.setsockopt(
            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
        )
        if not self._startup():
            return
        out = self.request.sendall
        out(_msg(b"R", struct.pack("!I", 0)))  # AuthenticationOk
        for k, v in (
            ("server_version", "13.0 (risingwave-tpu)"),
            ("client_encoding", "UTF8"),
        ):
            out(_msg(b"S", k.encode() + b"\0" + v.encode() + b"\0"))
        out(_msg(b"K", struct.pack("!II", 0, 0)))  # BackendKeyData
        out(_msg(b"Z", b"I"))

        session: SqlSession = self.server.session  # type: ignore[attr-defined]
        stmts: dict = {}  # prepared name -> sql
        portals: dict = {}  # portal name -> (bound sql, T already sent)
        skip_to_sync = False  # error in a pipeline: discard until Sync
        while True:
            head = self._recv_exact(5)
            if head is None:
                return
            tag, length = head[:1], struct.unpack("!I", head[1:])[0]
            body = self._recv_exact(length - 4)
            if body is None:
                return
            if tag == b"X":  # Terminate
                return
            if skip_to_sync:
                # protocol: after an extended-protocol error, queued
                # messages are DISCARDED until the client's Sync
                if tag == b"S":
                    skip_to_sync = False
                    out(_msg(b"Z", b"I"))
                continue
            try:
                if tag == b"Q":
                    sql = body.rstrip(b"\0").decode()
                    # concurrency is the SESSION's contract now: DDL/
                    # DML/stateful reads serialize on the runtime lock
                    # inside execute(), and shared-arrangement SELECTs
                    # serve lock-free off published versions — a global
                    # server lock here would put every reader back in
                    # one file line (the pre-serving-tier behavior)
                    cols, tag_str = session.execute(sql)
                    if cols:
                        out(self._row_description(cols))
                        out(self._data_rows(cols))
                    out(_msg(b"C", tag_str.encode() + b"\0"))
                    out(_msg(b"Z", b"I"))
                elif tag == b"P":  # Parse
                    name, rest = body.split(b"\0", 1)
                    sql, _rest = rest.split(b"\0", 1)
                    stmts[name] = sql.decode()
                    out(_msg(b"1"))  # ParseComplete
                elif tag == b"B":  # Bind
                    portal, rest = body.split(b"\0", 1)
                    stmt, rest = rest.split(b"\0", 1)
                    off = 0
                    (nfmt,) = struct.unpack_from("!h", rest, off)
                    off += 2
                    fmts = struct.unpack_from(f"!{nfmt}h", rest, off)
                    off += 2 * nfmt
                    if any(f == 1 for f in fmts):
                        raise ValueError(
                            "binary parameter format unsupported "
                            "(bind text-format parameters)"
                        )
                    (nparams,) = struct.unpack_from("!h", rest, off)
                    off += 2
                    params = []
                    for _ in range(nparams):
                        (plen,) = struct.unpack_from("!i", rest, off)
                        off += 4
                        if plen < 0:
                            params.append(None)
                        else:
                            params.append(rest[off : off + plen])
                            off += plen
                    if stmt not in stmts:
                        raise KeyError(
                            f"unknown prepared statement {stmt!r}"
                        )
                    portals[portal] = [
                        self._bind_params(stmts[stmt], params),
                        False,
                    ]
                    out(_msg(b"2"))  # BindComplete
                elif tag == b"D":  # Describe
                    kind, name = body[:1], body[1:].split(b"\0", 1)[0]
                    sql = (
                        portals.get(name, [None])[0]
                        if kind == b"P"
                        else stmts.get(name)
                    )
                    if kind == b"S":
                        # ParameterDescription is MANDATORY before the
                        # row shape when describing a statement
                        import re as _re

                        nps = (
                            max(
                                (
                                    int(m)
                                    for m in _re.findall(
                                        r"\$(\d+)", sql or ""
                                    )
                                ),
                                default=0,
                            )
                        )
                        out(
                            _msg(
                                b"t",
                                struct.pack("!h", nps)
                                + struct.pack("!I", 0) * nps,  # unknown
                            )
                        )
                    desc = None
                    if sql is not None and sql.lstrip()[:6].lower() == "select":
                        # infer the row shape WITHOUT executing
                        desc = self._describe_select(session, sql)
                    if desc is None:
                        out(_msg(b"n"))  # NoData
                    else:
                        out(desc)
                        if kind == b"P" and name in portals:
                            portals[name][1] = True
                elif tag == b"E":  # Execute
                    name = body.split(b"\0", 1)[0]
                    if name not in portals:
                        raise KeyError(f"unknown portal {name!r}")
                    sql, t_sent = portals[name]
                    cols, tag_str = session.execute(sql)
                    if cols:
                        if not t_sent:
                            out(self._row_description(cols))
                        out(self._data_rows(cols))
                    out(_msg(b"C", tag_str.encode() + b"\0"))
                elif tag == b"C":  # Close
                    kind, name = body[:1], body[1:].split(b"\0", 1)[0]
                    (portals if kind == b"P" else stmts).pop(name, None)
                    out(_msg(b"3"))  # CloseComplete
                elif tag == b"S":  # Sync
                    out(_msg(b"Z", b"I"))
                elif tag == b"H":  # Flush
                    pass
                else:
                    out(_err(f"unsupported message {tag!r}"))
                    out(_msg(b"Z", b"I"))
            except Exception as e:  # noqa: BLE001 — surface as pg error
                out(_err(str(e)))
                if tag == b"Q":
                    out(_msg(b"Z", b"I"))
                else:
                    # extended protocol: discard the rest of the
                    # pipeline; the client's Sync elicits ReadyForQuery
                    skip_to_sync = True

    @staticmethod
    def _describe_select(session: SqlSession, sql: str):
        """RowDescription for a SELECT from the typing layer (names +
        logical types; no execution, no side effects)."""
        try:
            import re as _re

            from risingwave_tpu.sql import parser as P
            from risingwave_tpu.sql.typing import (
                expand_star,
                infer_output_fields,
                output_name,
            )
            from risingwave_tpu.types import DataType

            # unbound parameters parse as NULL for shape inference
            stmt = P.parse(_re.sub(r"\$\d+", "NULL", sql))
            if not isinstance(stmt, P.Select):
                return None
            stmt = expand_star(stmt, session.catalog, strict=False)
            inferred = infer_output_fields(stmt, session.catalog)
            fields = b""
            names = [
                output_name(it, i) for i, it in enumerate(stmt.items)
            ]
            oid_map = {
                DataType.BOOLEAN: _OID_BOOL,
                DataType.FLOAT32: _OID_FLOAT8,
                DataType.FLOAT64: _OID_FLOAT8,
                DataType.VARCHAR: _OID_TEXT,
                DataType.JSONB: _OID_TEXT,
                DataType.DECIMAL: _OID_TEXT,
            }
            for nm in names:
                f = inferred.get(nm)
                oid = oid_map.get(f.dtype, _OID_INT8) if f else _OID_INT8
                fields += nm.encode() + b"\0" + struct.pack(
                    "!IhIhih", 0, 0, oid, -1, -1, 0
                )
            return _msg(b"T", struct.pack("!h", len(names)) + fields)
        except Exception:  # noqa: BLE001 — Describe is best-effort
            return None


def _err(message: str) -> bytes:
    payload = (
        b"SERROR\0"
        + b"CXX000\0"
        + b"M" + message.encode() + b"\0"
        + b"\0"
    )
    return _msg(b"E", payload)


class PgServer:
    """Serve a SqlSession over pgwire on 127.0.0.1."""

    def __init__(self, session: SqlSession, port: int = 0):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Srv(("127.0.0.1", port), _Conn)
        self._srv.session = session  # type: ignore[attr-defined]
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )

    def start(self) -> "PgServer":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
