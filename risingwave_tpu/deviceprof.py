"""Device-level observability for the fused engine: compiled-artifact
roofline, in-program telemetry lanes, and fused-stage attribution.

PR 10 collapsed the barrier into ONE donated device program — and
blinded every host-side observability layer doing it: the dispatch
profiler sees one opaque ``fused:<frag>`` dispatch, and
``achieved_bw_frac`` was computed from host byte guesses
(state-delta + chunk bytes) that describe nothing the donated program
actually reads or writes. This module is the "compile the whole query,
then explain where the cycles went" discipline (PAPERS.md: TiLT) with
the padded-lane waste accounting of region-based SIMD state layouts —
three legs:

1. **Compiled-artifact roofline** (:func:`analyze_lowerable`,
   ``DEVICEPROF.ensure_program``): every fused program / compiled
   kernel bucket is introspected once via
   ``jit(...).lower(...).compile()`` cost+memory analysis — FLOPs,
   bytes accessed, argument/output/temp HBM footprint, compile ms,
   executable size — feeding ``compile_ms{fn,bucket}`` /
   ``executable_bytes{fn,bucket}`` / ``fused_modeled_bytes{fragment}``
   gauges and the per-barrier MODELED bytes figure EpochTrace now
   prefers over the legacy host guess. Bytes decompose into useful vs
   padding using the bucketing layer's live/capacity lane accounting
   (the telemetry lanes provide live counts at zero extra reads).
2. **In-program telemetry** (``DEVICEPROF.note_telemetry``): the fused
   step packs device-computed per-member stats (rows applied, dirty
   groups, state occupancy, masked-lane fill) into the SAME staged
   scalar lane the barrier already reads — per-member visibility at
   zero extra dispatches and zero new host syncs. The wrapper calls
   ``note_telemetry`` when the pack materializes; gauges:
   ``fused_member_rows{fragment,member}``,
   ``fused_dirty_groups{fragment}``, ``fused_lane_fill_frac{fragment}``,
   ``padding_bytes_frac{fragment}``.
3. **Fused-stage attribution** (:func:`parse_fused_stages`): the fused
   program's apply / flush / mv_write / scalar_pack phases are wrapped
   in ``jax.named_scope`` (runtime/fused_step), so a ``jax_trace``
   capture segments the ONE program; the offline parser aggregates
   trace events back into ``fused_stage_ms{fragment,stage}`` — the
   68/31-style stage split that ranked the original fusion worklist,
   now measured INSIDE the device program.

Hot-path contract (profiler.py/blackbox.py discipline): program
analysis is gated on ONE ``DEVICEPROF.enabled`` check (an analysis is
one extra AOT compile per distinct program bucket — arm it in bench /
tests, not in the steady serve path); telemetry recording always rides
(a dict build + a few gauge sets per barrier, budgeted <1% of a steady
barrier by ``perf_gate --roofline``). Module import stays jax-free so
reader CLIs can parse traces from plain processes; jax is imported
lazily inside the analysis path only.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from risingwave_tpu.metrics import REGISTRY

__all__ = [
    "DEVICEPROF",
    "DeviceProfiler",
    "FUSED_STAGES",
    "analyze_lowerable",
    "analyze_nexmark",
    "parse_fused_stages",
]

# the fused program's named-scope stages (runtime/fused_step wraps its
# phases in jax.named_scope("fused/<stage>"))
FUSED_STAGES = ("apply", "flush", "mv_write", "scalar_pack")


# ---------------------------------------------------------------------------
# leg 1: compiled-artifact introspection
# ---------------------------------------------------------------------------


def _cost_dict(compiled) -> Dict[str, float]:
    """``Compiled.cost_analysis()`` across jax versions: a dict, a
    list of dicts (one per computation), or None."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — analysis degrades, never faults
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca if isinstance(ca, dict) else {}


def analyze_lowerable(lower_fn: Callable[[], object]) -> Dict:
    """Compile the thunk's lowered program and introspect the
    executable: XLA cost analysis (flops, bytes accessed) + memory
    analysis (argument/output/temp footprint, generated code size),
    with the wall-clock compile cost. ``lower_fn`` returns a
    ``jax.stages.Lowered`` (e.g. ``jitted.lower(*abstract_args)``) —
    abstract ShapeDtypeStruct args keep this allocation-free."""
    t0 = time.perf_counter()
    compiled = lower_fn().compile()
    compile_ms = (time.perf_counter() - t0) * 1e3
    cost = _cost_dict(compiled)
    out = {
        "compile_ms": round(compile_ms, 3),
        "flops": float(cost.get("flops", 0.0) or 0.0),
        "bytes_accessed": int(cost.get("bytes accessed", 0.0) or 0.0),
        "argument_bytes": 0,
        "output_bytes": 0,
        "temp_bytes": 0,
        "executable_bytes": 0,
    }
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out["argument_bytes"] = int(ma.argument_size_in_bytes)
            out["output_bytes"] = int(ma.output_size_in_bytes)
            out["temp_bytes"] = int(ma.temp_size_in_bytes)
            out["executable_bytes"] = int(ma.generated_code_size_in_bytes)
    except Exception:  # noqa: BLE001 — memory analysis is per-backend
        pass
    # the modeled-bytes-per-dispatch figure: XLA's own accounting of
    # what the program touches; fall back to the HBM footprint when a
    # backend reports no per-op byte costs
    if not out["bytes_accessed"]:
        out["bytes_accessed"] = (
            out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
        )
    return out


class DeviceProfiler:
    """Process-wide device-program observability registry.

    ``programs`` maps (fn, bucket) -> one compiled-artifact analysis;
    ``fragments`` maps fragment label -> the modeled bytes of the
    LAST program bucket that fragment dispatched (the per-barrier
    modeled-traffic figure); ``telemetry`` holds each fragment's last
    packed-lane telemetry. All reads are cheap snapshots for
    bench / dashboard / flight-recorder consumers."""

    def __init__(self):
        self.enabled = False  # gates ANALYSIS (one AOT compile/bucket)
        self._lock = threading.Lock()
        self.programs: Dict[tuple, Dict] = {}
        self.fragments: Dict[str, Dict] = {}
        self.telemetry: Dict[str, Dict] = {}
        self.telemetry_host_ms = 0.0  # cumulative note_telemetry cost
        self.analysis_errors = 0
        # analyses DEFERRED off the dispatch path: ensure_program only
        # enqueues the (abstract) lower thunk; the AOT compile runs at
        # flush_analyses() — report/roofline time, never inside a
        # measured barrier (a bucket's analysis compile is ~1-2s on
        # CPU, ~30-40s on a tunneled TPU)
        self._pending: Dict[tuple, tuple] = {}
        # fragments that DISPATCHED since the last consumed barrier:
        # the model only attributes a fragment's modeled bytes to
        # barriers it actually ran in (an idle barrier must model ZERO
        # traffic, or achieved_bw_frac reports phantom bandwidth)
        self._dispatched: set = set()

    # -- lifecycle --------------------------------------------------------
    def arm(self) -> "DeviceProfiler":
        self.enabled = True
        return self

    def disarm(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self.programs.clear()
            self.fragments.clear()
            self.telemetry.clear()
            self._pending.clear()
            self._dispatched.clear()
            self.telemetry_host_ms = 0.0
            self.analysis_errors = 0

    def from_env(self) -> "DeviceProfiler":
        """RW_DEVICEPROF=1 arms analysis; =0 disarms (env wins in both
        directions, the RW_PROFILE precedence)."""
        raw = os.environ.get("RW_DEVICEPROF")
        if raw is None:
            return self
        if raw.strip().lower() in ("1", "on", "true"):
            self.arm()
        elif raw.strip().lower() in ("0", "off", "false"):
            self.disarm()
        return self

    def on_recovery(self) -> None:
        """Recovery/rebuild hook (runtime calls this next to
        PROFILER.abort_captures): drop per-barrier telemetry — the
        rebuilt fragments' first barrier repopulates it — but KEEP the
        program analyses: recovery re-fuses into the same compiled
        programs (FusedPlan is value-hashable), so the roofline stays
        valid. Deviceprof opens no device sessions, so there is no
        capture window to orphan."""
        with self._lock:
            self.telemetry.clear()

    # -- leg 1: program analysis ------------------------------------------
    def ensure_program(
        self,
        fn: str,
        bucket: str,
        lower_fn: Callable[[], object],
        fragment: Optional[str] = None,
    ) -> Optional[Dict]:
        """Register one (fn, bucket) program for analysis. The hot
        path only ENQUEUES the abstract lower thunk (a dict insert);
        the AOT compile runs at :meth:`flush_analyses` — report /
        roofline time, never inside a measured barrier. With
        ``fragment``, the bucket's modeled bytes become that
        fragment's per-barrier traffic figure once analyzed. Never
        raises — observability must not change execution."""
        if not self.enabled:
            return None
        key = (fn, bucket)
        with self._lock:
            if fragment is not None:
                self._dispatched.add(fragment)
            hit = self.programs.get(key)
            if hit is None:
                if key not in self._pending:
                    self._pending[key] = (lower_fn, fragment)
                elif fragment is not None:
                    self._pending[key] = (self._pending[key][0], fragment)
                return None
        if fragment is not None and "error" not in hit:
            self._bind_fragment(key, fragment, hit)
        return hit

    def flush_analyses(self) -> int:
        """Run every deferred program analysis (one AOT lower+compile
        per new bucket — ~1-2s on CPU, ~30-40s on a tunneled TPU).
        Call OUTSIDE timed windows: bench calls it before collecting
        roofline fields, the perf gate before checking, report() for
        ad-hoc reads. Returns the number of programs analyzed."""
        if not self.enabled:
            return 0
        with self._lock:
            pending, self._pending = dict(self._pending), {}
        done = 0
        for key, (lower_fn, fragment) in pending.items():
            fn, bucket = key
            try:
                hit = analyze_lowerable(lower_fn)
                done += 1
            except Exception as e:  # noqa: BLE001 — never fault
                hit = {"error": repr(e)}
                self.analysis_errors += 1
            with self._lock:
                self.programs[key] = hit
            if "error" not in hit:
                REGISTRY.gauge("compile_ms").set(
                    hit["compile_ms"], fn=fn, bucket=bucket
                )
                REGISTRY.gauge("executable_bytes").set(
                    float(hit["executable_bytes"]), fn=fn, bucket=bucket
                )
                if fragment is not None:
                    self._bind_fragment(key, fragment, hit)
        return done

    def _bind_fragment(self, key: tuple, fragment: str, hit: Dict) -> None:
        with self._lock:
            self.fragments[fragment] = {
                "fn": key[0],
                "bucket": key[1],
                "modeled_bytes": hit["bytes_accessed"],
            }
        REGISTRY.gauge("fused_modeled_bytes").set(
            float(hit["bytes_accessed"]), fragment=fragment
        )

    # -- leg 2: telemetry -------------------------------------------------
    def note_telemetry(self, fragment: str, tel: Dict) -> None:
        """One fragment-barrier's packed-lane telemetry (host side of
        the staged read the barrier already pays — zero device IO
        here). ``tel`` carries ``member_rows`` ({member: rows}),
        ``dirty_groups``, ``occupancy`` ({member: live}),
        ``lanes_total``/``rows_in`` (masked-lane fill), and
        ``padding_bytes_frac`` (live-vs-capacity over the members'
        state lanes, weighted by state bytes)."""
        t0 = time.perf_counter()
        with self._lock:
            self.telemetry[fragment] = tel
            self._dispatched.add(fragment)
        g = REGISTRY.gauge("fused_member_rows")
        for member, rows in (tel.get("member_rows") or {}).items():
            g.set(float(rows), fragment=fragment, member=member)
        if "dirty_groups" in tel:
            REGISTRY.gauge("fused_dirty_groups").set(
                float(tel["dirty_groups"]), fragment=fragment
            )
        if "lane_fill_frac" in tel:
            REGISTRY.gauge("fused_lane_fill_frac").set(
                tel["lane_fill_frac"], fragment=fragment
            )
        if "padding_bytes_frac" in tel:
            REGISTRY.gauge("padding_bytes_frac").set(
                tel["padding_bytes_frac"], fragment=fragment
            )
        self.telemetry_host_ms += (time.perf_counter() - t0) * 1e3

    # -- read surfaces ----------------------------------------------------
    def barrier_model(self, consume: bool = False) -> Dict:
        """The per-barrier modeled-traffic figure EpochTrace consumes:
        modeled bytes across the fused fragments that DISPATCHED since
        the last consumed barrier (each fragment's last analyzed
        bucket) and the telemetry-weighted padding fraction. An idle
        barrier — no fused dispatch since the last consume — models
        ZERO traffic, never phantom bandwidth. ``consume`` clears the
        dispatched set (once per barrier, by its trace)."""
        with self._lock:
            active = set(self._dispatched)
            if consume:
                self._dispatched.clear()
            frags = {
                k: dict(v)
                for k, v in self.fragments.items()
                if k in active
            }
            tel = {k: dict(v) for k, v in self.telemetry.items()}
        total = 0
        weighted = 0.0
        for name, f in frags.items():
            mb = int(f.get("modeled_bytes", 0))
            total += mb
            frac = (tel.get(name) or {}).get("padding_bytes_frac")
            if frac is not None:
                weighted += mb * float(frac)
        return {
            "modeled_bytes": total,
            "padding_frac": round(weighted / total, 6) if total else 0.0,
            "fragments": sorted(active),
        }

    def steady_model(self) -> Dict:
        """The steady-state per-barrier figure over ALL analyzed
        fragments (each one's last bucket), regardless of the
        per-barrier dispatch gating — what bench/gate report AFTER a
        run whose barriers already consumed their own models."""
        with self._lock:
            frags = {k: dict(v) for k, v in self.fragments.items()}
            tel = {k: dict(v) for k, v in self.telemetry.items()}
        mb = sum(int(f.get("modeled_bytes", 0)) for f in frags.values())
        weighted = sum(
            int(f.get("modeled_bytes", 0))
            * float((tel.get(n) or {}).get("padding_bytes_frac", 0.0))
            for n, f in frags.items()
        )
        return {
            "modeled_bytes": mb,
            "padding_frac": round(weighted / mb, 6) if mb else 0.0,
        }

    def consume_barrier(self) -> Dict:
        """One barrier's deviceprof tail, CONSUMED: the modeled-bytes
        model plus the compact telemetry of the fragments that ran in
        it (flight-recorder ``tel`` shape). EpochTrace.finalize calls
        this once per barrier; fragments that did not dispatch again
        stop appearing — a post-mortem timeline never shows a fragment
        applying rows on barriers it never ran in."""
        model = self.barrier_model(consume=True)
        with self._lock:
            tel = {
                frag: {
                    "rows": t.get("member_rows", {}),
                    "dirty": t.get("dirty_groups", 0),
                }
                for frag, t in self.telemetry.items()
                if frag in model["fragments"]
            }
        return {
            "modeled_bytes": model["modeled_bytes"],
            "padding_frac": model["padding_frac"],
            "tel": tel,
        }

    def report(self, flush: bool = True) -> Dict:
        """The BENCH-JSON / dashboard surface. ``flush`` runs deferred
        analyses first (one AOT compile per pending bucket) — callers
        on a live serving path (the dashboard HTTP handler) pass
        ``flush=False`` and render the snapshot as-is: a page load
        must never compile, least of all concurrently with a measured
        barrier loop."""
        if flush:
            self.flush_analyses()
        with self._lock:
            programs = {
                f"{fn}|{bucket}": dict(v)
                for (fn, bucket), v in self.programs.items()
            }
            fragments = {k: dict(v) for k, v in self.fragments.items()}
            telemetry = {k: dict(v) for k, v in self.telemetry.items()}
        return {
            "enabled": self.enabled,
            "programs": programs,
            "fragments": fragments,
            "telemetry": telemetry,
            "telemetry_host_ms": round(self.telemetry_host_ms, 3),
            "analysis_errors": self.analysis_errors,
        }

    def roofline_fields(
        self, prefix: str, n_barriers: int, seconds: float
    ) -> Dict:
        """Bench integration: the ``{q}_roofline`` artifact block —
        modeled bytes per barrier from the compiled executable,
        decomposed into useful vs padding traffic, with the measured
        achieved/useful bandwidth fractions over the run."""
        from risingwave_tpu.epoch_trace import hbm_peak_gbps

        rep = self.report()  # flushes deferred compiles OUTSIDE the timer
        model = self.steady_model()
        mb = model["modeled_bytes"]
        frac = model["padding_frac"]
        useful = int(mb * (1.0 - frac))
        peak = hbm_peak_gbps()
        total_bytes = mb * max(n_barriers, 0)
        bw = total_bytes / seconds / 1e9 if seconds > 0 else 0.0
        achieved = bw / peak if peak else 0.0
        return {
            f"{prefix}_roofline": {
                "modeled_bytes_per_barrier": mb,
                "useful_bytes_per_barrier": useful,
                "padding_bytes_per_barrier": mb - useful,
                "padding_bytes_frac": frac,
                "achieved_bw_frac": round(achieved, 6),
                "useful_bw_frac": round(achieved * (1.0 - frac), 6),
                "hbm_peak_gbps": peak,
                "programs": rep["programs"],
                "telemetry": rep["telemetry"],
                "telemetry_host_ms": round(self.telemetry_host_ms, 3),
            }
        }


# ---------------------------------------------------------------------------
# leg 3: fused-stage attribution (offline trace-event parser)
# ---------------------------------------------------------------------------


def _iter_trace_events(source):
    """Yield chrome-trace event dicts from a dict, a JSON(.gz) file,
    or a directory (scanned recursively for ``*.trace.json.gz`` — the
    jax.profiler TensorBoard layout — and plain ``*.json`` traces)."""
    if isinstance(source, dict):
        yield from source.get("traceEvents", [])
        return
    if os.path.isdir(source):
        hits: List[str] = []
        for dirpath, _dirs, files in os.walk(source):
            for f in files:
                if f.endswith(".trace.json.gz") or f.endswith(
                    ".trace.json"
                ):
                    hits.append(os.path.join(dirpath, f))
        for p in sorted(hits):
            yield from _iter_trace_events(p)
        return
    opener = gzip.open if source.endswith(".gz") else open
    with opener(source, "rt") as f:
        doc = json.load(f)
    yield from (doc or {}).get("traceEvents", [])


def parse_fused_stages(source, record: bool = True) -> Dict:
    """Aggregate a jax profiler capture's trace events back into the
    fused program's stage split.

    Any complete ("X") or begin/end ("B"/"E") event whose name carries
    a ``fused/<stage>`` scope contributes its duration to that stage;
    ``fused:<label>`` host annotations (the wrapper's TraceAnnotation
    around the dispatch) attribute the whole parse to a fragment when
    exactly one label appears, else "-". Durations land in
    ``fused_stage_ms{fragment,stage}`` (unless ``record=False``) and
    come back as ``{"fragment": ..., "stages_ms": {stage: ms}}`` —
    the device-side 68/31 split, per stage, per capture."""
    stages: Dict[str, float] = {}
    labels = set()
    open_begins: Dict[tuple, float] = {}
    for ev in _iter_trace_events(source):
        name = str(ev.get("name", ""))
        if "fused:" in name:
            labels.add(name.split("fused:", 1)[1].split("/")[0].strip())
            continue
        if "fused/" not in name:
            continue
        stage = name.split("fused/", 1)[1].split("/")[0].strip()
        if not stage:
            continue
        ph = ev.get("ph", "X")
        if ph == "X":
            stages[stage] = stages.get(stage, 0.0) + float(
                ev.get("dur", 0.0)
            )
        elif ph == "B":
            open_begins[(stage, ev.get("tid"), ev.get("pid"))] = float(
                ev.get("ts", 0.0)
            )
        elif ph == "E":
            t0 = open_begins.pop(
                (stage, ev.get("tid"), ev.get("pid")), None
            )
            if t0 is not None:
                stages[stage] = stages.get(stage, 0.0) + (
                    float(ev.get("ts", 0.0)) - t0
                )
    fragment = labels.pop() if len(labels) == 1 else "-"
    stages_ms = {k: round(v / 1e3, 4) for k, v in stages.items()}
    if record:
        h = REGISTRY.histogram("fused_stage_ms")
        for stage, ms in stages_ms.items():
            h.observe(ms, fragment=fragment, stage=stage)
    return {"fragment": fragment, "stages_ms": stages_ms}


# ---------------------------------------------------------------------------
# corpus analyzer: per-executor compiled-step roofline on CPU
# ---------------------------------------------------------------------------


def analyze_executor_steps(
    chain: Sequence[object],
    spec,
    fragment: str,
    capacities: Sequence[int] = (),
) -> Dict[str, Dict]:
    """Cost/memory-analyze every traceable executor step in one chain
    over its abstract input spec (the fusion analyzer's schema
    threading, reused): ``{executor_label: analysis}``. Executors
    without a trace contract (or with an unknown upstream schema) are
    skipped — the analyzer never guesses a lane width."""
    import jax

    from risingwave_tpu.analysis.fusion_analyzer import (
        _contract,
        _lint_info,
        _thread_spec,
    )

    out: Dict[str, Dict] = {}
    for idx, ex in enumerate(chain):
        contract = _contract(ex)
        step = (contract or {}).get("trace_step")
        if step is not None and spec is not None:
            caps = tuple(capacities) or (spec.capacity,)
            for cap in caps:
                label = f"{fragment}/{idx}:{type(ex).__name__}@{cap}"
                abstract = spec.with_capacity(cap).abstract()
                try:
                    out[label] = analyze_lowerable(
                        lambda s=step, a=abstract: jax.jit(s).lower(a)
                    )
                except Exception as e:  # noqa: BLE001 — skip, don't fault
                    out[label] = {"error": repr(e)}
        spec = _thread_spec(spec, ex, _lint_info(ex))
    return out


def analyze_nexmark(
    only: Optional[str] = None, capacity: int = 1 << 8
) -> Dict[str, Dict[str, Dict]]:
    """Compiled-step roofline over the Nexmark corpus twins (q5/q7/q8
    plus the planner-built q5u): per executor, per fragment section,
    the XLA cost/memory analysis of its traceable step — runs whole on
    CPU (abstract lowering, no device state touched). The test-suite
    sanity bar: every query yields at least one analysis with nonzero
    flops and bytes accessed."""
    from risingwave_tpu.analysis.fusion_analyzer import _spec_from_schema
    from risingwave_tpu.analysis.lint import (
        NEXMARK_SOURCE_SCHEMAS,
        build_nexmark_corpus,
    )
    from risingwave_tpu.runtime.fragmenter import fragment_chains

    names = (only,) if only else ("q5", "q5u", "q7", "q8")
    built = {}
    for q in names:
        if q == "q5u":
            # the unified path's plan (SQL -> planner), same engine
            from risingwave_tpu.connectors.nexmark import BID_SCHEMA
            from risingwave_tpu.sql import Catalog, StreamPlanner

            built["q5u"] = StreamPlanner(
                Catalog({"bid": BID_SCHEMA}), capacity=capacity
            ).plan(
                "CREATE MATERIALIZED VIEW q5 AS SELECT auction, "
                "window_start, count(*) AS num FROM HOP(bid, date_time, "
                "INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
                "GROUP BY auction, window_start"
            )
        else:
            built.update(build_nexmark_corpus(capacity=capacity, only=q))
    out: Dict[str, Dict[str, Dict]] = {}
    for q, planned in built.items():
        schemas = NEXMARK_SOURCE_SCHEMAS.get(
            "q5" if q == "q5u" else q, {}
        )
        rep: Dict[str, Dict] = {}
        for frag, sections in fragment_chains(planned.pipeline).items():
            for side, chain in sections.items():
                if not chain:
                    continue
                spec = _spec_from_schema(
                    schemas.get(side)
                    if side in ("single", "left", "right")
                    else None
                )
                rep.update(
                    analyze_executor_steps(chain, spec, f"{frag}/{side}")
                )
        out[q] = rep
    return out


# the process singleton (profiler.PROFILER / blackbox.RECORDER idiom)
DEVICEPROF = DeviceProfiler()
